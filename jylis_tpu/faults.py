"""Deterministic fault injection: named failpoints at every I/O seam.

The cluster's delta anti-entropy is deliberately fire-and-forget (the
delta-CRDT model assumes lossy dissemination healed by periodic sync),
which means the interesting bugs live in the failure envelope AROUND the
lattice math: a dial that hangs, an fsync that fails mid-rotation, a
frame corrupted on the wire, a process that dies between journal append
and snapshot cut. Before this module every crash drill was a bespoke
monkeypatch; failpoints make the failure modes injectable by NAME, from
the environment or from test code, so the drill matrix
(tests/test_drill_matrix.py) can iterate {fault class} x {injection
site} combinatorially over a real cluster.

Arming syntax (``JYLIS_FAILPOINTS`` env var or the ``--failpoints``
flag; comma-separated)::

    cluster.dial=error:3,journal.fsync=sleep:0.2,codec.decode=corrupt

i.e. ``name=action[:arg[:budget]]``. Actions:

* ``error[:budget]``   — raise :class:`FaultError` at the point;
* ``sleep:secs[:budget]`` — delay the operation by ``secs`` seconds
  (``asyncio.sleep`` at async points, ``time.sleep`` at thread points);
* ``corrupt[:budget]`` — deterministically flip one byte of the data
  flowing through the point (degrades to ``error`` at data-less sites);
* ``crash[:budget]``   — hard-kill the process (``os._exit``), the
  SIGKILL-shaped drill; tests may install a handler instead;
* ``drop[:budget]``    — silently discard the data flowing through the
  point (the caller sees "success" and nothing is sent/written;
  degrades to ``error`` at data-less sites).

A ``budget`` bounds the number of firings: once exhausted the point
disarms itself, so a drill can inject "3 dial failures, then heal"
without coordinating a disarm. Hit counts survive disarming
(:func:`hits`), so drills can assert the site actually fired.

:class:`FaultError` subclasses ``ConnectionError`` (hence ``OSError``):
every I/O seam in this repo already routes those into its real
failure-recovery path, so an injected error exercises the handling code
that a genuine failure would, not an injection-only special case.

**Unarmed points are free.** ``point(name)`` / ``async_point(name)``
cost exactly one dict miss when nothing is armed — the registry dict is
empty unless ``JYLIS_FAILPOINTS`` is set or a test armed a point — so
the seams stay on the hot path permanently (verified by bench-smoke).

Every ``faults.point(...)`` name in the product tree must be declared
in ``scripts/jlint/failpoints_manifest.json`` with a one-line
description (jlint pass 4; ``--write-manifest`` regenerates), so the
set of injectable seams is reviewed, documented, and can't rot.
"""

from __future__ import annotations

import asyncio
import os
import threading
import time

ACTIONS = ("error", "sleep", "corrupt", "crash", "drop")

ENV_VAR = "JYLIS_FAILPOINTS"

CRASH_EXIT_CODE = 86  # distinguishes an injected crash from real faults


class FaultError(ConnectionError):
    """Raised by an armed ``error`` failpoint (and by ``corrupt``/
    ``drop`` at data-less sites). A ``ConnectionError`` so the existing
    ``except (ConnectionError, ...)`` / ``except OSError`` recovery
    paths at every seam treat it exactly like the real failure it
    stands in for."""


class FaultSpecError(ValueError):
    """Malformed ``JYLIS_FAILPOINTS`` / ``--failpoints`` spec."""


class _Point:
    __slots__ = ("name", "action", "arg", "budget")

    def __init__(self, name: str, action: str, arg: float | None, budget: int | None):
        self.name = name
        self.action = action
        self.arg = arg
        self.budget = budget


# The registry. Reads (the hot-path dict miss) are GIL-atomic; all
# mutation — arming, budget consumption, hit counting — happens under
# _lock because points fire from the event loop AND from worker threads
# (journal writer, snapshot to_thread).
_lock = threading.Lock()
_armed: dict[str, _Point] = {}
_hits: dict[str, int] = {}  # cumulative, survives disarm (drill asserts)

# `crash` handler: tests that drive nodes in-process replace this (an
# os._exit would take the test runner down with the "node")
_crash_handler = None


def parse_spec(spec: str) -> list[tuple[str, str, float | None, int | None]]:
    """``name=action[:arg[:budget]]`` comma list -> arm() argument tuples."""
    out = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        if "=" not in item:
            raise FaultSpecError(f"failpoint spec {item!r} lacks '=action'")
        name, rhs = item.split("=", 1)
        parts = rhs.split(":")
        action, args = parts[0], parts[1:]
        if action not in ACTIONS:
            raise FaultSpecError(
                f"unknown failpoint action {action!r} in {item!r} "
                f"(expected one of {', '.join(ACTIONS)})"
            )
        arg: float | None = None
        if action == "sleep":
            if not args:
                raise FaultSpecError(f"sleep needs seconds: {item!r}")
            try:
                arg = float(args.pop(0))
            except ValueError:
                raise FaultSpecError(f"bad sleep seconds in {item!r}") from None
        budget: int | None = None
        if args:
            try:
                budget = int(args.pop(0))
            except ValueError:
                raise FaultSpecError(f"bad hit budget in {item!r}") from None
            if budget <= 0:
                raise FaultSpecError(f"hit budget must be positive: {item!r}")
        if args:
            raise FaultSpecError(f"trailing arguments in {item!r}")
        out.append((name.strip(), action, arg, budget))
    return out


def arm(name: str, action: str, arg: float | None = None, budget: int | None = None) -> None:
    """Programmatic arming (tests); env/flag arming goes via arm_spec."""
    if action not in ACTIONS:
        raise FaultSpecError(f"unknown failpoint action {action!r}")
    if action == "sleep" and arg is None:
        raise FaultSpecError("sleep needs seconds")
    with _lock:
        _armed[name] = _Point(name, action, arg, budget)


def arm_spec(spec: str) -> None:
    for name, action, arg, budget in parse_spec(spec):
        arm(name, action, arg, budget)


def disarm(name: str) -> None:
    with _lock:
        _armed.pop(name, None)


def reset() -> None:
    """Disarm everything and zero the hit counters (test teardown)."""
    with _lock:
        _armed.clear()
        _hits.clear()


def hits(name: str) -> int:
    """Cumulative firings of a point (survives disarm/budget exhaustion)."""
    with _lock:
        return _hits.get(name, 0)


def armed_points() -> dict[str, str]:
    """{name: action} snapshot of what is currently armed."""
    with _lock:
        return {n: p.action for n, p in _armed.items()}


def set_crash_handler(fn) -> None:
    """Replace the ``crash`` action's process-kill (in-process drills);
    pass None to restore ``os._exit``."""
    global _crash_handler
    _crash_handler = fn


def _consume(p: _Point) -> bool:
    """Take one firing from the point's budget; False when exhausted
    (the point disarms itself and the caller proceeds normally)."""
    with _lock:
        if _armed.get(p.name) is not p:
            return False  # re-armed/disarmed concurrently: newest wins
        if p.budget is not None:
            if p.budget <= 0:
                _armed.pop(p.name, None)
                return False
            p.budget -= 1
            if p.budget == 0:
                _armed.pop(p.name, None)  # last firing happens below
        _hits[p.name] = _hits.get(p.name, 0) + 1
        return True


def _corrupt(data: bytes) -> bytes:
    """Deterministic single-byte flip, mid-buffer: the same input always
    corrupts the same way, so a drill failure replays exactly."""
    b = bytearray(data)
    if b:
        b[len(b) // 2] ^= 0x01
    return bytes(b)


def _fire(p: _Point, data):
    if p.action == "error":
        raise FaultError(f"failpoint {p.name}: injected error")
    if p.action == "crash":
        handler = _crash_handler
        if handler is not None:
            handler(p.name)
            return data
        os._exit(CRASH_EXIT_CODE)
    if p.action == "corrupt":
        if data is None:  # data-less site: degrade to error (documented)
            raise FaultError(f"failpoint {p.name}: corrupt at data-less site")
        return _corrupt(data)
    if p.action == "drop":
        if data is None:
            raise FaultError(f"failpoint {p.name}: drop at data-less site")
        return None
    raise AssertionError(f"unhandled action {p.action}")  # pragma: no cover


def point(name: str, data: bytes | None = None):
    """The synchronous failpoint. Unarmed: one dict miss, returns
    ``data`` unchanged. Armed: ``error`` raises FaultError, ``sleep``
    blocks (thread contexts — the journal writer, to_thread snapshot
    work; loop-side sync seams keep injected sleeps short), ``corrupt``
    returns mutated bytes, ``drop`` returns None (caller discards
    silently), ``crash`` kills the process."""
    p = _armed.get(name)
    if p is None:
        return data
    if not _consume(p):
        return data
    if p.action == "sleep":
        time.sleep(p.arg)
        return data
    return _fire(p, data)


async def async_point(name: str, data: bytes | None = None):
    """The event-loop failpoint: identical semantics to :func:`point`
    except ``sleep`` awaits ``asyncio.sleep`` so an injected delay
    stalls only the task at the seam, never the whole loop."""
    p = _armed.get(name)
    if p is None:
        return data
    if not _consume(p):
        return data
    if p.action == "sleep":
        await asyncio.sleep(p.arg)
        return data
    return _fire(p, data)


# env arming happens at import: spawned drill nodes (and operators)
# arm via JYLIS_FAILPOINTS with no code involved
_env_spec = os.environ.get(ENV_VAR, "")
if _env_spec:
    arm_spec(_env_spec)
