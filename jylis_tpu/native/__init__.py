"""Native (C++) fast paths, loaded via ctypes with pure-Python fallbacks.

The reference's hot codecs are compiled Pony (SURVEY.md §2: pony-resp's
CommandParser, the framing/serialise codec); their rebuild equivalents are
C++ under native/, built into ``libjylis_native.so`` by `make native` (or
lazily here on first import when a toolchain is available — the build is
two translation units and takes well under a second).

``lib()`` returns the loaded CDLL or None; callers must keep working
without it (the Python implementations are the semantic oracles).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC_DIR = os.path.join(_REPO_ROOT, "native")
# deployed images/wheels carry the prebuilt .so without the C++ sources:
# JYLIS_NATIVE_SO points straight at it (see Dockerfile), or `make
# release` bundles it next to this file inside the wheel
_PKG_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "libjylis_native.so")
_SO_PATH = (
    os.environ.get("JYLIS_NATIVE_SO")
    or (_PKG_SO if os.path.exists(_PKG_SO) else None)
    or os.path.join(_SRC_DIR, "libjylis_native.so")
)

_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    try:
        sources = [
            os.path.join(_SRC_DIR, f)
            for f in sorted(os.listdir(_SRC_DIR))
            if f.endswith(".cpp")
        ]
    except OSError:  # no source checkout (installed wheel / image)
        return False
    if not sources:
        return False
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", _SO_PATH]
            + sources,
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _stale() -> bool:
    if not os.path.isdir(_SRC_DIR):
        return False  # prebuilt .so without sources is never stale
    so_mtime = os.path.getmtime(_SO_PATH)
    return any(
        os.path.getmtime(os.path.join(_SRC_DIR, f)) > so_mtime
        for f in os.listdir(_SRC_DIR)
        if f.endswith(".cpp")
    )


def _declare_codec(cdll: ctypes.CDLL) -> None:
    """Signatures for the cluster wire codec (native/cluster_codec.cpp)."""
    c = ctypes
    p64 = c.POINTER(c.c_int64)
    sigs = {
        # encode: (..., out, cap) -> bytes written or -1
        "jy_push_counters_encode": (
            c.c_int64,
            [c.c_char_p, c.c_int64, c.c_int64, c.c_char_p, c.c_void_p,
             c.c_void_p, c.c_int32, c.c_void_p, c.c_void_p, c.c_void_p,
             c.c_void_p, c.c_int64],
        ),
        "jy_push_treg_encode": (
            c.c_int64,
            [c.c_char_p, c.c_int64, c.c_int64, c.c_char_p, c.c_void_p,
             c.c_void_p, c.c_char_p, c.c_void_p, c.c_void_p, c.c_void_p,
             c.c_void_p, c.c_int64],
        ),
        "jy_push_tlog_encode": (
            c.c_int64,
            [c.c_char_p, c.c_int64, c.c_int64, c.c_char_p, c.c_void_p,
             c.c_void_p, c.c_void_p, c.c_char_p, c.c_void_p, c.c_void_p,
             c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64],
        ),
        # measure/decode: -> 0 ok, -1 malformed, -2 fall back to oracle
        "jy_push_counters_measure": (
            c.c_int32, [c.c_char_p, c.c_int64, c.c_int32, p64, p64],
        ),
        "jy_push_counters_decode": (
            c.c_int32,
            [c.c_char_p, c.c_int64, c.c_int32, c.c_void_p, c.c_void_p,
             c.c_void_p, c.c_void_p, c.c_void_p],
        ),
        "jy_push_treg_measure": (c.c_int32, [c.c_char_p, c.c_int64, p64]),
        "jy_push_treg_decode": (
            c.c_int32,
            [c.c_char_p, c.c_int64, c.c_void_p, c.c_void_p, c.c_void_p,
             c.c_void_p, c.c_void_p],
        ),
        "jy_push_tlog_measure": (c.c_int32, [c.c_char_p, c.c_int64, p64, p64]),
        "jy_push_tlog_decode": (
            c.c_int32,
            [c.c_char_p, c.c_int64, c.c_void_p, c.c_void_p, c.c_void_p,
             c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p],
        ),
        "jy_push_ujson_encode": (
            c.c_int64,
            [c.c_char_p, c.c_int64, c.c_int64, c.c_char_p, c.c_void_p,
             c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p,
             c.c_char_p, c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p,
             c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64],
        ),
        # UJSON wire fast paths (native/ujson_planes.cpp)
        "jy_ujson_split_measure": (c.c_int32, [c.c_char_p, c.c_int64, p64]),
        "jy_ujson_split": (
            c.c_int32,
            [c.c_char_p, c.c_int64, c.c_void_p, c.c_void_p, c.c_void_p,
             c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p],
        ),
        "jy_ujson_grid_fill": (
            c.c_int32,
            [c.c_char_p, c.c_int64, c.c_void_p, c.c_void_p, c.c_void_p,
             c.c_int32, c.c_int64, c.c_int64, c.c_int64, c.c_void_p,
             c.c_int64, c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p,
             c.c_void_p, p64, c.c_void_p, c.c_void_p, p64, p64],
        ),
    }
    for fn_name, (restype, argtypes) in sigs.items():
        fn = getattr(cdll, fn_name)
        fn.restype = restype
        fn.argtypes = argtypes


def lib() -> ctypes.CDLL | None:
    """The native library, building it on first use if needed/possible."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        if not os.path.exists(_SO_PATH) or _stale():
            if not _build():
                return None
        cdll = ctypes.CDLL(_SO_PATH)
        cdll.resp_scan.restype = ctypes.c_int32
        cdll.resp_scan.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
        cdll.resp_scan_many.restype = ctypes.c_int32
        cdll.resp_scan_many.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
        _declare_codec(cdll)
        _lib = cdll
    except OSError:
        _lib = None
    return _lib
