"""Native (C++) fast paths, loaded via ctypes with pure-Python fallbacks.

The reference's hot codecs are compiled Pony (SURVEY.md §2: pony-resp's
CommandParser, the framing/serialise codec); their rebuild equivalents are
C++ under native/, built into ``libjylis_native.so`` by `make native` (or
lazily here on first import when a toolchain is available — the build is
two translation units and takes well under a second).

``lib()`` returns the loaded CDLL or None; callers must keep working
without it (the Python implementations are the semantic oracles).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_SRC_DIR, "libjylis_native.so")

_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    sources = [
        os.path.join(_SRC_DIR, f)
        for f in sorted(os.listdir(_SRC_DIR))
        if f.endswith(".cpp")
    ]
    if not sources:
        return False
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", _SO_PATH]
            + sources,
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _stale() -> bool:
    so_mtime = os.path.getmtime(_SO_PATH)
    return any(
        os.path.getmtime(os.path.join(_SRC_DIR, f)) > so_mtime
        for f in os.listdir(_SRC_DIR)
        if f.endswith(".cpp")
    )


def lib() -> ctypes.CDLL | None:
    """The native library, building it on first use if needed/possible."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        if not os.path.exists(_SO_PATH) or _stale():
            if not _build():
                return None
        cdll = ctypes.CDLL(_SO_PATH)
        cdll.resp_scan.restype = ctypes.c_int32
        cdll.resp_scan.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
        cdll.resp_scan_many.restype = ctypes.c_int32
        cdll.resp_scan_many.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
        _lib = cdll
    except OSError:
        _lib = None
    return _lib
