"""ctypes wrapper for the native serving engine (native/engine.h,
counter_engine.cpp + serve_engine.cpp).

`ServeEngine` owns the host state every command touches — the
GCOUNT/PNCOUNT counter tables, the TREG winner/pending/delta registers,
the TLOG pending/merged-view/delta logs, the validated UJSON write
queue and the UJSON per-(key, path) render memo — and applies whole
pipelined command bursts per FFI call. The Python dict
backends (models/counter_table.py, models/treg_table.py,
models/tlog_table.py) remain the semantic oracles and the fallback when
no toolchain is available; differential tests pin the equivalence.
"""

from __future__ import annotations

import ctypes
import struct

import numpy as np

from . import lib

G = 0
PN = 1

_OUT_CAP = 1 << 16
_MAX_ARGS = 1024

# jy_tlog_export_merged's "view unavailable" sentinel (serve_engine.cpp)
_TLOG_UNAVAILABLE = -1 - (1 << 40)


def _declare(c: ctypes.CDLL) -> None:
    ct = ctypes
    vp, i32, i64, u64, u8p = (
        ct.c_void_p, ct.c_int32, ct.c_int64, ct.c_uint64, ct.c_char_p,
    )
    pi64 = ct.POINTER(ct.c_int64)
    pi32 = ct.POINTER(ct.c_int32)
    pvp = ct.POINTER(ct.c_void_p)
    pu64 = ct.POINTER(ct.c_uint64)
    sigs = {
        "jy_eng_new": (vp, []),
        "jy_eng_free": (None, [vp]),
        "jy_eng_rows": (i64, [vp, i32]),
        "jy_eng_upsert": (i64, [vp, i32, u8p, i64]),
        "jy_eng_find": (i64, [vp, i32, u8p, i64]),
        "jy_eng_key": (None, [vp, i32, i64, pvp, pi64]),
        "jy_eng_inc": (None, [vp, i32, i64, i32, u64]),
        "jy_eng_is_foreign": (i32, [vp, i32, i64]),
        "jy_eng_set_foreign": (None, [vp, i32, i64]),
        "jy_eng_value": (u64, [vp, i32, i64]),
        "jy_eng_own": (u64, [vp, i32, i64, i32]),
        "jy_eng_own_max": (None, [vp, i32, i64, i32, u64]),
        "jy_eng_own_set": (i32, [vp, i32, i64]),
        "jy_eng_apply_drain": (None, [vp, i32, vp, vp, i64]),
        "jy_eng_export_pending": (i64, [vp, i32, vp, vp, vp, i64, i32]),
        "jy_eng_dirty_count": (i64, [vp, i32]),
        "jy_eng_pend_count": (i64, [vp, i32]),
        "jy_eng_export_dirty": (i64, [vp, i32, vp, vp, vp, vp, i64]),
        "jy_eng_export_sync_dirty": (i64, [vp, i32, vp, i64]),
        "jy_treg_export_sync_dirty": (i64, [vp, vp, i64]),
        "jy_tlog_export_sync_dirty": (i64, [vp, vp, i64]),
        "jy_treg_deltas_info": (None, [vp, pi64, pi64, pi64]),
        "jy_treg_export_deltas_bulk": (
            None, [vp, vp, vp, vp, vp, vp, vp, vp],
        ),
        "jy_tlog_deltas_info": (None, [vp, pi64, pi64, pi64]),
        "jy_tlog_export_deltas_bulk": (
            None, [vp, vp, vp, vp, vp, vp, vp, vp],
        ),
        "jy_tlog_export_pend_bulk": (i64, [vp, vp, i64, vp, vp, vp, i64]),
        "jy_tlog_vals_info": (None, [vp, i32, pi64, pi64]),
        "jy_tlog_export_vals": (None, [vp, i32, vp, vp, vp]),
        # TREG
        "jy_treg_rows": (i64, [vp]),
        "jy_treg_upsert": (i64, [vp, u8p, i64]),
        "jy_treg_find": (i64, [vp, u8p, i64]),
        "jy_treg_key": (None, [vp, i64, pvp, pi64]),
        "jy_treg_write": (None, [vp, i64, u64, u8p, i64]),
        "jy_treg_note_delta": (None, [vp, i64, u64, u8p, i64]),
        "jy_treg_winner": (i32, [vp, i64, pu64, pvp, pi64]),
        "jy_treg_pend_count": (i64, [vp]),
        "jy_treg_export_pend": (i64, [vp, vp, vp, i64]),
        "jy_treg_pend_val": (None, [vp, i64, pvp, pi64]),
        "jy_treg_fold_pend": (None, [vp]),
        "jy_treg_delta_count": (i64, [vp]),
        "jy_treg_export_deltas": (i64, [vp, vp, vp, i64]),
        "jy_treg_delta_val": (None, [vp, i64, pvp, pi64]),
        "jy_treg_clear_deltas": (None, [vp]),
        # TLOG
        "jy_tlog_rows": (i64, [vp]),
        "jy_tlog_upsert": (i64, [vp, u8p, i64]),
        "jy_tlog_find": (i64, [vp, u8p, i64]),
        "jy_tlog_key": (None, [vp, i64, pvp, pi64]),
        "jy_tlog_ins": (None, [vp, i64, u64, u8p, i64]),
        "jy_tlog_conv_entry": (None, [vp, i64, u64, u8p, i64]),
        "jy_tlog_conv_cutoff": (None, [vp, i64, u64]),
        "jy_tlog_size": (i64, [vp, i64]),
        "jy_tlog_len_cache": (i64, [vp, i64]),
        "jy_tlog_cut_cache": (u64, [vp, i64]),
        "jy_tlog_cutoff_view": (u64, [vp, i64]),
        "jy_tlog_pend_cutoff": (u64, [vp, i64]),
        "jy_tlog_quiescent": (i32, [vp, i64]),
        "jy_tlog_gen": (u64, [vp, i64]),
        "jy_tlog_pend_len": (i64, [vp, i64]),
        "jy_tlog_pend_rows_count": (i64, [vp]),
        "jy_tlog_row_overdue": (i32, [vp]),
        "jy_tlog_touched_rows": (i64, [vp, vp, i64]),
        "jy_tlog_touched_count": (i64, [vp]),
        "jy_tlog_export_base": (i64, [vp, i64, vp, vp, i64]),
        "jy_tlog_compact": (i32, [vp]),
        "jy_tlog_base_valid": (i32, [vp, i64]),
        "jy_tlog_live_total": (i64, [vp]),
        "jy_tlog_export_pend": (i64, [vp, i64, vp, vp, i64]),
        "jy_tlog_val": (None, [vp, i32, pvp, pi64]),
        "jy_tlog_intern": (i32, [vp, u8p, i64]),
        "jy_tlog_finish_row": (None, [vp, i64, i64, u64]),
        "jy_tlog_finish_end": (None, [vp]),
        "jy_tlog_set_base": (None, [vp, i64, i64, vp, vp]),
        "jy_tlog_export_merged": (i64, [vp, i64, vp, vp, i64]),
        "jy_tlog_delta_rows_count": (i64, [vp]),
        "jy_tlog_export_delta_rows": (i64, [vp, vp, i64]),
        "jy_tlog_export_delta": (i64, [vp, i64, vp, vp, i64]),
        "jy_tlog_delta_cutoff": (u64, [vp, i64]),
        "jy_tlog_delta_raise_cutoff": (None, [vp, i64, u64]),
        "jy_tlog_clear_deltas": (None, [vp]),
        "jy_eng_served": (None, [vp, vp]),
        # UJSON queue + render memo
        "jy_uq_count": (i64, [vp]),
        "jy_uq_bytes": (i64, [vp]),
        "jy_uq_data": (i64, [vp, vp, i64]),
        "jy_uq_clear": (None, [vp]),
        "jy_uj_upsert": (i64, [vp, u8p, i64]),
        "jy_uj_memo_put": (None, [vp, i64, u8p, i64, u8p, i64]),
        "jy_uj_invalidate": (None, [vp, u8p, i64, u8p, i64, i32]),
        "jy_uj_memo_len": (i64, [vp, u8p, i64]),
        # batch applier
        "jy_eng_scan_apply2": (
            i32,
            [vp, vp, i64, vp, i64, pi64, pi64, vp, vp, i32, pi32, vp],
        ),
    }
    for fn_name, (restype, argtypes) in sigs.items():
        fn = getattr(c, fn_name)
        fn.restype = restype
        fn.argtypes = argtypes


_declared = False


class ServeEngine:
    """One native engine instance = all five data-type tables of one node."""

    def __init__(self, cdll):
        global _declared
        if not _declared:
            _declare(cdll)
            _declared = True
        self._lib = cdll
        self._h = cdll.jy_eng_new()
        self._out = (ctypes.c_uint8 * _OUT_CAP)()
        self._offs = (ctypes.c_int64 * _MAX_ARGS)()
        self._lens = (ctypes.c_int64 * _MAX_ARGS)()
        self._changed = (ctypes.c_int32 * 5)()
        self._tlog_vals: list[bytes] = []  # native vid -> bytes mirror

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.jy_eng_free(self._h)
            self._h = None

    # ---- counter table ops -------------------------------------------------

    def rows(self, which: int) -> int:
        return self._lib.jy_eng_rows(self._h, which)

    def upsert(self, which: int, key: bytes) -> int:
        return self._lib.jy_eng_upsert(self._h, which, key, len(key))

    def find(self, which: int, key: bytes) -> int:
        return self._lib.jy_eng_find(self._h, which, key, len(key))

    def key_of(self, which: int, row: int) -> bytes:
        ptr = ctypes.c_void_p()
        n = ctypes.c_int64()
        self._lib.jy_eng_key(self._h, which, row, ctypes.byref(ptr), ctypes.byref(n))
        return ctypes.string_at(ptr, n.value)

    def inc(self, which: int, row: int, polarity: int, amount: int) -> None:
        self._lib.jy_eng_inc(self._h, which, row, polarity, amount)

    def is_foreign(self, which: int, row: int) -> bool:
        return bool(self._lib.jy_eng_is_foreign(self._h, which, row))

    def set_foreign(self, which: int, row: int) -> None:
        self._lib.jy_eng_set_foreign(self._h, which, row)

    def value(self, which: int, row: int) -> int:
        return self._lib.jy_eng_value(self._h, which, row)

    def own(self, which: int, row: int, polarity: int) -> int:
        return self._lib.jy_eng_own(self._h, which, row, polarity)

    def own_max(self, which: int, row: int, polarity: int, v: int) -> None:
        self._lib.jy_eng_own_max(self._h, which, row, polarity, v)

    def apply_drain(self, which: int, rows, values) -> None:
        rows = np.ascontiguousarray(rows, np.int64)
        values = np.ascontiguousarray(values, np.uint64)
        self._lib.jy_eng_apply_drain(
            self._h, which,
            rows.ctypes.data, values.ctypes.data, len(rows),
        )

    def export_pending(self, which: int, clear: bool = True):
        cap = 256
        while True:
            rows = np.empty(cap, np.int64)
            vp = np.empty(cap, np.uint64)
            vn = np.empty(cap, np.uint64)
            n = self._lib.jy_eng_export_pending(
                self._h, which,
                rows.ctypes.data, vp.ctypes.data, vn.ctypes.data, cap,
                1 if clear else 0,
            )
            if n >= 0:
                return rows[:n], vp[:n], vn[:n]
            cap = -n

    def dirty_count(self, which: int) -> int:
        return self._lib.jy_eng_dirty_count(self._h, which)

    def pend_count(self, which: int) -> int:
        return self._lib.jy_eng_pend_count(self._h, which)

    def export_dirty(self, which: int):
        cap = 256
        while True:
            rows = np.empty(cap, np.int64)
            op = np.empty(cap, np.uint64)
            on = np.empty(cap, np.uint64)
            sb = np.empty(cap, np.uint8)
            n = self._lib.jy_eng_export_dirty(
                self._h, which,
                rows.ctypes.data, op.ctypes.data, on.ctypes.data,
                sb.ctypes.data, cap,
            )
            if n >= 0:
                return rows[:n], op[:n], on[:n], sb[:n]
            cap = -n

    def own_set(self, which: int, row: int) -> int:
        """bit0 = P own ever written, bit1 = N own ever written."""
        return self._lib.jy_eng_own_set(self._h, which, row)

    def _export_sync_dirty(self, fn, *head) -> list[int]:
        cap = 256
        while True:
            rows = np.empty(cap, np.int64)
            n = fn(self._h, *head, rows.ctypes.data, cap)
            if n >= 0:
                return rows[:n].tolist()
            cap = -n

    def export_sync_dirty(self, which: int) -> list[int]:
        """Counter rows changed since the last digest pass; clears."""
        return self._export_sync_dirty(
            self._lib.jy_eng_export_sync_dirty, which
        )

    def treg_export_sync_dirty(self) -> list[int]:
        return self._export_sync_dirty(self._lib.jy_treg_export_sync_dirty)

    def tlog_export_sync_dirty(self) -> list[int]:
        return self._export_sync_dirty(self._lib.jy_tlog_export_sync_dirty)

    # ---- TREG table ops ----------------------------------------------------

    def treg_rows(self) -> int:
        return self._lib.jy_treg_rows(self._h)

    def treg_upsert(self, key: bytes) -> int:
        return self._lib.jy_treg_upsert(self._h, key, len(key))

    def treg_find(self, key: bytes) -> int:
        return self._lib.jy_treg_find(self._h, key, len(key))

    def treg_key_of(self, row: int) -> bytes:
        ptr = ctypes.c_void_p()
        n = ctypes.c_int64()
        self._lib.jy_treg_key(self._h, row, ctypes.byref(ptr), ctypes.byref(n))
        return ctypes.string_at(ptr, n.value)

    def treg_write(self, row: int, ts: int, value: bytes) -> None:
        self._lib.jy_treg_write(self._h, row, ts, value, len(value))

    def treg_note_delta(self, row: int, ts: int, value: bytes) -> None:
        self._lib.jy_treg_note_delta(self._h, row, ts, value, len(value))

    def treg_winner(self, row: int):
        ts = ctypes.c_uint64()
        ptr = ctypes.c_void_p()
        n = ctypes.c_int64()
        if not self._lib.jy_treg_winner(
            self._h, row, ctypes.byref(ts), ctypes.byref(ptr), ctypes.byref(n)
        ):
            return None
        return ts.value, ctypes.string_at(ptr, n.value)

    def treg_pend_count(self) -> int:
        return self._lib.jy_treg_pend_count(self._h)

    def treg_export_pend(self):
        """[(row, ts, value)] without clearing (clear = treg_fold_pend)."""
        cap = 256
        while True:
            rows = np.empty(cap, np.int64)
            ts = np.empty(cap, np.uint64)
            n = self._lib.jy_treg_export_pend(
                self._h, rows.ctypes.data, ts.ctypes.data, cap
            )
            if n >= 0:
                break
            cap = -n
        ptr = ctypes.c_void_p()
        ln = ctypes.c_int64()
        out = []
        for i in range(n):
            self._lib.jy_treg_pend_val(
                self._h, int(rows[i]), ctypes.byref(ptr), ctypes.byref(ln)
            )
            out.append((int(rows[i]), int(ts[i]), ctypes.string_at(ptr, ln.value)))
        return out

    def treg_fold_pend(self) -> None:
        self._lib.jy_treg_fold_pend(self._h)

    def treg_delta_count(self) -> int:
        return self._lib.jy_treg_delta_count(self._h)

    def treg_flush_deltas(self):
        """Sorted [(key, (value, ts))]; clears the delta window. ONE bulk
        FFI pass — per-row round-trips made a 20k-key flush ~12x slower
        than the dict oracle."""
        n = ctypes.c_int64()
        vb = ctypes.c_int64()
        kb = ctypes.c_int64()
        self._lib.jy_treg_deltas_info(
            self._h, ctypes.byref(n), ctypes.byref(vb), ctypes.byref(kb)
        )
        n = n.value
        if n == 0:
            return []
        ts = np.empty(n, np.uint64)
        vo = np.empty(n, np.int64)
        vl = np.empty(n, np.int64)
        ko = np.empty(n, np.int64)
        kl = np.empty(n, np.int64)
        vblob = np.empty(max(vb.value, 1), np.uint8)
        kblob = np.empty(max(kb.value, 1), np.uint8)
        self._lib.jy_treg_export_deltas_bulk(
            self._h, ts.ctypes.data, vo.ctypes.data, vl.ctypes.data,
            vblob.ctypes.data, ko.ctypes.data, kl.ctypes.data,
            kblob.ctypes.data,
        )
        self._lib.jy_treg_clear_deltas(self._h)
        vbytes = vblob.tobytes()
        kbytes = kblob.tobytes()
        out = [
            (kbytes[o : o + ln], (vbytes[vo_ : vo_ + vl_], t))
            for o, ln, vo_, vl_, t in zip(
                ko.tolist(), kl.tolist(), vo.tolist(), vl.tolist(),
                ts.tolist(),
            )
        ]
        out.sort()
        return out

    # ---- TLOG table ops ----------------------------------------------------

    def _tlog_val(self, vid: int) -> bytes:
        vals = self._tlog_vals
        if vid >= len(vals):
            self._tlog_refill_vals()
        return vals[vid]

    def _tlog_refill_vals(self) -> None:
        """Mirror every native-interned value from the current mirror
        length up, in ONE bulk export."""
        lo = len(self._tlog_vals)
        n = ctypes.c_int64()
        nb = ctypes.c_int64()
        self._lib.jy_tlog_vals_info(
            self._h, lo, ctypes.byref(n), ctypes.byref(nb)
        )
        if n.value <= 0:
            return
        off = np.empty(n.value, np.int64)
        ln = np.empty(n.value, np.int64)
        blob = np.empty(max(nb.value, 1), np.uint8)
        self._lib.jy_tlog_export_vals(
            self._h, lo, off.ctypes.data, ln.ctypes.data, blob.ctypes.data
        )
        data = blob.tobytes()
        self._tlog_vals.extend(
            data[o : o + l] for o, l in zip(off.tolist(), ln.tolist())
        )

    def tlog_rows(self) -> int:
        return self._lib.jy_tlog_rows(self._h)

    def tlog_upsert(self, key: bytes) -> int:
        return self._lib.jy_tlog_upsert(self._h, key, len(key))

    def tlog_find(self, key: bytes) -> int:
        return self._lib.jy_tlog_find(self._h, key, len(key))

    def tlog_key_of(self, row: int) -> bytes:
        ptr = ctypes.c_void_p()
        n = ctypes.c_int64()
        self._lib.jy_tlog_key(self._h, row, ctypes.byref(ptr), ctypes.byref(n))
        return ctypes.string_at(ptr, n.value)

    def tlog_ins(self, row: int, ts: int, value: bytes) -> None:
        self._lib.jy_tlog_ins(self._h, row, ts, value, len(value))

    def tlog_conv_entry(self, row: int, ts: int, value: bytes) -> None:
        self._lib.jy_tlog_conv_entry(self._h, row, ts, value, len(value))

    def tlog_conv_cutoff(self, row: int, c: int) -> None:
        self._lib.jy_tlog_conv_cutoff(self._h, row, c)

    def tlog_size(self, row: int) -> int:
        return self._lib.jy_tlog_size(self._h, row)

    def tlog_len_cache(self, row: int) -> int:
        return self._lib.jy_tlog_len_cache(self._h, row)

    def tlog_cut_cache(self, row: int) -> int:
        return self._lib.jy_tlog_cut_cache(self._h, row)

    def tlog_cutoff_view(self, row: int) -> int:
        return self._lib.jy_tlog_cutoff_view(self._h, row)

    def tlog_pend_cutoff(self, row: int) -> int:
        return self._lib.jy_tlog_pend_cutoff(self._h, row)

    def tlog_quiescent(self, row: int) -> bool:
        return bool(self._lib.jy_tlog_quiescent(self._h, row))

    def tlog_gen(self, row: int) -> int:
        return self._lib.jy_tlog_gen(self._h, row)

    def tlog_pend_len(self, row: int) -> int:
        return self._lib.jy_tlog_pend_len(self._h, row)

    def tlog_pend_rows_count(self) -> int:
        return self._lib.jy_tlog_pend_rows_count(self._h)

    def tlog_row_overdue(self) -> bool:
        return bool(self._lib.jy_tlog_row_overdue(self._h))

    def tlog_touched_rows(self) -> list[int]:
        cap = 256
        while True:
            rows = np.empty(cap, np.int64)
            n = self._lib.jy_tlog_touched_rows(self._h, rows.ctypes.data, cap)
            if n >= 0:
                return rows[:n].tolist()
            cap = -n

    def tlog_touched_count(self) -> int:
        return self._lib.jy_tlog_touched_count(self._h)

    def tlog_base_entries(self, row: int):
        """[(ts, value)] of the drained row content when the carried base
        is valid; None when the repo must gather it from the device."""
        cap = 64
        while True:
            ts = np.empty(cap, np.uint64)
            vid = np.empty(cap, np.int32)
            n = self._lib.jy_tlog_export_base(
                self._h, row, ts.ctypes.data, vid.ctypes.data, cap
            )
            if n == _TLOG_UNAVAILABLE:
                return None
            if n >= 0:
                return [
                    (int(ts[i]), self._tlog_val(int(vid[i]))) for i in range(n)
                ]
            cap = -n

    def tlog_compact(self) -> bool:
        """Native value-interner compaction; resets the vid mirror when a
        remap happened."""
        if self._lib.jy_tlog_compact(self._h):
            self._tlog_vals.clear()
            return True
        return False

    def tlog_base_valid(self, row: int) -> bool:
        return bool(self._lib.jy_tlog_base_valid(self._h, row))

    def tlog_live_total(self) -> int:
        return self._lib.jy_tlog_live_total(self._h)

    def tlog_export_pend(self, row: int) -> list[tuple[int, bytes]]:
        cap = max(self.tlog_pend_len(row), 1)
        ts = np.empty(cap, np.uint64)
        vid = np.empty(cap, np.int32)
        n = self._lib.jy_tlog_export_pend(
            self._h, row, ts.ctypes.data, vid.ctypes.data, cap
        )
        assert n >= 0
        return [(int(ts[i]), self._tlog_val(int(vid[i]))) for i in range(n)]

    def tlog_export_pend_bulk(self, rows: list[int]):
        """{row: [(ts, value)]} for the drain's row set in one call."""
        nrows = len(rows)
        if nrows == 0:
            return {}
        rows_a = np.asarray(rows, np.int64)
        counts = np.empty(nrows, np.int64)
        cap = 256
        while True:
            ts = np.empty(cap, np.uint64)
            vid = np.empty(cap, np.int32)
            total = self._lib.jy_tlog_export_pend_bulk(
                self._h, rows_a.ctypes.data, nrows, counts.ctypes.data,
                ts.ctypes.data, vid.ctypes.data, cap,
            )
            if total >= 0:
                break
            cap = -total
        if int(vid[:total].max(initial=-1)) >= len(self._tlog_vals):
            self._tlog_refill_vals()
        vals = self._tlog_vals
        ts_l = ts[:total].tolist()
        vid_l = vid[:total].tolist()
        out = {}
        e = 0
        for row, c in zip(rows, counts.tolist()):
            out[row] = [(ts_l[j], vals[vid_l[j]]) for j in range(e, e + c)]
            e += c
        return out

    def tlog_intern(self, value: bytes) -> int:
        return self._lib.jy_tlog_intern(self._h, value, len(value))

    def tlog_finish_row(self, row: int, length: int, cut: int) -> None:
        self._lib.jy_tlog_finish_row(self._h, row, length, cut)

    def tlog_finish_end(self) -> None:
        self._lib.jy_tlog_finish_end(self._h)

    def tlog_set_base(self, row: int, entries) -> None:
        """entries: [(ts, value bytes)] — the drained row content."""
        n = len(entries)
        ts = np.empty(max(n, 1), np.uint64)
        vid = np.empty(max(n, 1), np.int32)
        for i, (t, v) in enumerate(entries):
            ts[i] = t
            vid[i] = self.tlog_intern(v)
        self._lib.jy_tlog_set_base(
            self._h, row, n, ts.ctypes.data, vid.ctypes.data
        )

    def tlog_merged_entries(self, row: int):
        """[(ts, value)] of the merged view, unsorted; None when the
        drained base is unknown (call tlog_size / tlog_set_base first)."""
        cap = 64
        while True:
            ts = np.empty(cap, np.uint64)
            vid = np.empty(cap, np.int32)
            n = self._lib.jy_tlog_export_merged(
                self._h, row, ts.ctypes.data, vid.ctypes.data, cap
            )
            if n == _TLOG_UNAVAILABLE:
                return None
            if n >= 0:
                return [
                    (int(ts[i]), self._tlog_val(int(vid[i]))) for i in range(n)
                ]
            cap = -n

    def tlog_deltas_size(self) -> int:
        return self._lib.jy_tlog_delta_rows_count(self._h)

    def tlog_delta_raise_cutoff(self, row: int, c: int) -> None:
        self._lib.jy_tlog_delta_raise_cutoff(self._h, row, c)

    def tlog_flush_deltas(self):
        """Sorted [(key, (entries latest-first, cutoff))]; clears. ONE
        bulk FFI pass (see treg_flush_deltas)."""
        n = ctypes.c_int64()
        te = ctypes.c_int64()
        kb = ctypes.c_int64()
        self._lib.jy_tlog_deltas_info(
            self._h, ctypes.byref(n), ctypes.byref(te), ctypes.byref(kb)
        )
        n = n.value
        if n == 0:
            return []
        counts = np.empty(n, np.int64)
        cutoffs = np.empty(n, np.uint64)
        ts_flat = np.empty(max(te.value, 1), np.uint64)
        vid_flat = np.empty(max(te.value, 1), np.int32)
        ko = np.empty(n, np.int64)
        kl = np.empty(n, np.int64)
        kblob = np.empty(max(kb.value, 1), np.uint8)
        self._lib.jy_tlog_export_deltas_bulk(
            self._h, counts.ctypes.data, cutoffs.ctypes.data,
            ts_flat.ctypes.data, vid_flat.ctypes.data,
            ko.ctypes.data, kl.ctypes.data, kblob.ctypes.data,
        )
        self._lib.jy_tlog_clear_deltas(self._h)
        if int(vid_flat[: te.value].max(initial=-1)) >= len(self._tlog_vals):
            self._tlog_refill_vals()
        vals = self._tlog_vals
        kbytes = kblob.tobytes()
        ts_l = ts_flat.tolist()
        vid_l = vid_flat.tolist()
        out = []
        e = 0
        for i, (c, cut, o, ln) in enumerate(
            zip(counts.tolist(), cutoffs.tolist(), ko.tolist(), kl.tolist())
        ):
            ents = sorted(
                ((ts_l[j], vals[vid_l[j]]) for j in range(e, e + c)),
                reverse=True,
            )
            e += c
            out.append((kbytes[o : o + ln], ([(v, t) for t, v in ents], cut)))
        out.sort()
        return out

    # the engine's changed/served-counter type order (serve_engine.cpp)
    TYPE_ORDER = ("GCOUNT", "PNCOUNT", "TREG", "TLOG", "UJSON")

    def served_counts(self) -> dict[str, int]:
        """Commands settled natively since startup, per data type."""
        out = np.zeros(5, np.uint64)
        self._lib.jy_eng_served(self._h, out.ctypes.data)
        return dict(zip(self.TYPE_ORDER, out.tolist()))

    # ---- UJSON render memo -------------------------------------------------

    @staticmethod
    def _uj_path_blob(path_args) -> bytes:
        """Path argument vector as the memo's length-prefixed blob key
        (binary-safe, and component-prefix == byte-prefix — engine.h).
        Components are CANONICALISED to the UTF-8 encoding of the
        errors="replace" decode the oracle applies (repo_ujson
        _decode_path): byte-distinct spellings that alias in the
        document alias in the memo too, so invalidation through one
        spelling can never leave another's render stale. The engine's
        bank-time invalidation uses raw bytes, which equal this
        canonical form exactly for valid UTF-8 — and it defers any
        write whose path is not valid UTF-8 (engine.h utf8_valid)."""
        return b"".join(
            struct.pack("<I", len(c)) + c
            for c in (
                bytes(p).decode("utf-8", "replace").encode()
                for p in path_args
            )
        )

    def uj_memo_put(self, key: bytes, path_args, reply: bytes) -> None:
        """Install the oracle-rendered GET reply for (key, path)."""
        row = self._lib.jy_uj_upsert(self._h, key, len(key))
        blob = self._uj_path_blob(path_args)
        self._lib.jy_uj_memo_put(
            self._h, row, blob, len(blob), reply, len(reply)
        )

    def uj_invalidate(self, key: bytes, path_args, subtree: bool) -> None:
        """Drop the renders a write at path can change: INS/RM
        (subtree=False) touch only renders at prefix paths; SET/CLR
        (subtree=True) rewrite the subtree, so both prefix directions."""
        blob = self._uj_path_blob(path_args)
        self._lib.jy_uj_invalidate(
            self._h, key, len(key), blob, len(blob), 1 if subtree else 0
        )

    def uj_memo_len(self, key: bytes) -> int:
        return self._lib.jy_uj_memo_len(self._h, key, len(key))

    # ---- UJSON queue -------------------------------------------------------

    def uq_count(self) -> int:
        return self._lib.jy_uq_count(self._h)

    def uq_drain(self) -> list[list[bytes]]:
        """Pop every banked UJSON write (INS/SET/RM/CLR) as its raw
        argument list (without the leading type word), in arrival
        order."""
        nbytes = self._lib.jy_uq_bytes(self._h)
        if nbytes == 0:
            return []
        blob = (ctypes.c_uint8 * nbytes)()
        got = self._lib.jy_uq_data(self._h, blob, nbytes)
        assert got == nbytes
        self._lib.jy_uq_clear(self._h)
        data = bytes(blob)
        out = []
        pos = 0
        while pos < len(data):
            (argc,) = struct.unpack_from("<I", data, pos)
            pos += 4
            args = []
            for _ in range(argc):
                (ln,) = struct.unpack_from("<I", data, pos)
                pos += 4
                args.append(data[pos : pos + ln])
                pos += ln
            out.append(args)
        return out

    # ---- the batch applier -------------------------------------------------

    def scan_apply(self, buf):
        """Apply a pipelined burst. Returns
        (rc, consumed, replies: bytes, unhandled: list[bytes] | None,
        changed: tuple of 5 per-type counts (G, PN, TREG, TLOG, UJSON));
        rc as documented in serve_engine.cpp."""
        if not buf:
            return 0, 0, b"", None, (0, 0, 0, 0, 0)
        base = ctypes.addressof(ctypes.c_char.from_buffer(buf))
        out_len = ctypes.c_int64()
        consumed = ctypes.c_int64()
        n_args = ctypes.c_int32()
        rc = self._lib.jy_eng_scan_apply2(
            self._h, ctypes.c_void_p(base), len(buf),
            self._out, _OUT_CAP, ctypes.byref(out_len),
            ctypes.byref(consumed),
            self._offs, self._lens, _MAX_ARGS, ctypes.byref(n_args),
            self._changed,
        )
        replies = ctypes.string_at(self._out, out_len.value)
        unhandled = None
        if rc == 1:
            view = memoryview(buf)
            unhandled = [
                bytes(view[self._offs[i] : self._offs[i] + self._lens[i]])
                for i in range(n_args.value)
            ]
            del view
        return rc, consumed.value, replies, unhandled, tuple(self._changed)


# the counter-only name the round-3 engine shipped under; kept for callers
CounterEngine = ServeEngine


def make_engine() -> ServeEngine | None:
    cdll = lib()
    return ServeEngine(cdll) if cdll is not None else None


def resolve_engine(engine):
    """The repos'/Database's shared engine-argument convention:
    "auto" -> a fresh native engine (None without a toolchain),
    "python" -> None (pure-Python table backends), anything else is
    passed through (a shared ServeEngine instance or None)."""
    if engine == "auto":
        return make_engine()
    if engine == "python":
        return None
    return engine
