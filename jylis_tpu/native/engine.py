"""ctypes wrapper for the native counter engine (native/counter_engine.cpp).

`CounterEngine` owns the GCOUNT/PNCOUNT host state (key table, own
contributions, serving values, dirty/pending/foreign bookkeeping) and
applies whole pipelined command bursts per FFI call. The Python dict
backend in models/repo_counters.py remains the semantic oracle and the
fallback when no toolchain is available; differential tests pin the
equivalence.
"""

from __future__ import annotations

import ctypes

import numpy as np

from . import lib

G = 0
PN = 1

_OUT_CAP = 1 << 16
_MAX_ARGS = 1024


def _declare(c: ctypes.CDLL) -> None:
    ct = ctypes
    c.jy_eng_new.restype = ct.c_void_p
    c.jy_eng_free.argtypes = [ct.c_void_p]
    c.jy_eng_rows.restype = ct.c_int64
    c.jy_eng_rows.argtypes = [ct.c_void_p, ct.c_int32]
    c.jy_eng_upsert.restype = ct.c_int64
    c.jy_eng_upsert.argtypes = [ct.c_void_p, ct.c_int32, ct.c_char_p, ct.c_int64]
    c.jy_eng_find.restype = ct.c_int64
    c.jy_eng_find.argtypes = [ct.c_void_p, ct.c_int32, ct.c_char_p, ct.c_int64]
    c.jy_eng_key.argtypes = [
        ct.c_void_p, ct.c_int32, ct.c_int64,
        ct.POINTER(ct.c_void_p), ct.POINTER(ct.c_int64),
    ]
    c.jy_eng_inc.argtypes = [
        ct.c_void_p, ct.c_int32, ct.c_int64, ct.c_int32, ct.c_uint64,
    ]
    c.jy_eng_is_foreign.restype = ct.c_int32
    c.jy_eng_is_foreign.argtypes = [ct.c_void_p, ct.c_int32, ct.c_int64]
    c.jy_eng_set_foreign.argtypes = [ct.c_void_p, ct.c_int32, ct.c_int64]
    c.jy_eng_value.restype = ct.c_uint64
    c.jy_eng_value.argtypes = [ct.c_void_p, ct.c_int32, ct.c_int64]
    c.jy_eng_own.restype = ct.c_uint64
    c.jy_eng_own.argtypes = [ct.c_void_p, ct.c_int32, ct.c_int64, ct.c_int32]
    c.jy_eng_own_max.argtypes = [
        ct.c_void_p, ct.c_int32, ct.c_int64, ct.c_int32, ct.c_uint64,
    ]
    c.jy_eng_apply_drain.argtypes = [
        ct.c_void_p, ct.c_int32, ct.c_void_p, ct.c_void_p, ct.c_int64,
    ]
    c.jy_eng_export_pending.restype = ct.c_int64
    c.jy_eng_export_pending.argtypes = [
        ct.c_void_p, ct.c_int32, ct.c_void_p, ct.c_void_p, ct.c_void_p,
        ct.c_int64, ct.c_int32,
    ]
    c.jy_eng_dirty_count.restype = ct.c_int64
    c.jy_eng_dirty_count.argtypes = [ct.c_void_p, ct.c_int32]
    c.jy_eng_pend_count.restype = ct.c_int64
    c.jy_eng_pend_count.argtypes = [ct.c_void_p, ct.c_int32]
    c.jy_eng_export_dirty.restype = ct.c_int64
    c.jy_eng_export_dirty.argtypes = [
        ct.c_void_p, ct.c_int32, ct.c_void_p, ct.c_void_p, ct.c_void_p,
        ct.c_void_p, ct.c_int64,
    ]
    c.jy_eng_own_set.restype = ct.c_int32
    c.jy_eng_own_set.argtypes = [ct.c_void_p, ct.c_int32, ct.c_int64]
    c.jy_eng_scan_apply.restype = ct.c_int32
    c.jy_eng_scan_apply.argtypes = [
        ct.c_void_p, ct.c_void_p, ct.c_int64,                      # buf
        ct.c_void_p, ct.c_int64, ct.POINTER(ct.c_int64),           # out
        ct.POINTER(ct.c_int64),                                    # consumed
        ct.c_void_p, ct.c_void_p, ct.c_int32, ct.POINTER(ct.c_int32),
        ct.POINTER(ct.c_int32), ct.POINTER(ct.c_int32),            # changed
    ]


_declared = False


class CounterEngine:
    """One native engine instance = both counter tables of one node."""

    def __init__(self, cdll):
        global _declared
        if not _declared:
            _declare(cdll)
            _declared = True
        self._lib = cdll
        self._h = cdll.jy_eng_new()
        self._out = (ctypes.c_uint8 * _OUT_CAP)()
        self._offs = (ctypes.c_int64 * _MAX_ARGS)()
        self._lens = (ctypes.c_int64 * _MAX_ARGS)()

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.jy_eng_free(self._h)
            self._h = None

    # ---- table ops ---------------------------------------------------------

    def rows(self, which: int) -> int:
        return self._lib.jy_eng_rows(self._h, which)

    def upsert(self, which: int, key: bytes) -> int:
        return self._lib.jy_eng_upsert(self._h, which, key, len(key))

    def find(self, which: int, key: bytes) -> int:
        return self._lib.jy_eng_find(self._h, which, key, len(key))

    def key_of(self, which: int, row: int) -> bytes:
        ptr = ctypes.c_void_p()
        n = ctypes.c_int64()
        self._lib.jy_eng_key(self._h, which, row, ctypes.byref(ptr), ctypes.byref(n))
        return ctypes.string_at(ptr, n.value)

    def inc(self, which: int, row: int, polarity: int, amount: int) -> None:
        self._lib.jy_eng_inc(self._h, which, row, polarity, amount)

    def is_foreign(self, which: int, row: int) -> bool:
        return bool(self._lib.jy_eng_is_foreign(self._h, which, row))

    def set_foreign(self, which: int, row: int) -> None:
        self._lib.jy_eng_set_foreign(self._h, which, row)

    def value(self, which: int, row: int) -> int:
        return self._lib.jy_eng_value(self._h, which, row)

    def own(self, which: int, row: int, polarity: int) -> int:
        return self._lib.jy_eng_own(self._h, which, row, polarity)

    def own_max(self, which: int, row: int, polarity: int, v: int) -> None:
        self._lib.jy_eng_own_max(self._h, which, row, polarity, v)

    def apply_drain(self, which: int, rows, values) -> None:
        rows = np.ascontiguousarray(rows, np.int64)
        values = np.ascontiguousarray(values, np.uint64)
        self._lib.jy_eng_apply_drain(
            self._h, which,
            rows.ctypes.data, values.ctypes.data, len(rows),
        )

    def export_pending(self, which: int, clear: bool = True):
        cap = 256
        while True:
            rows = np.empty(cap, np.int64)
            vp = np.empty(cap, np.uint64)
            vn = np.empty(cap, np.uint64)
            n = self._lib.jy_eng_export_pending(
                self._h, which,
                rows.ctypes.data, vp.ctypes.data, vn.ctypes.data, cap,
                1 if clear else 0,
            )
            if n >= 0:
                return rows[:n], vp[:n], vn[:n]
            cap = -n

    def dirty_count(self, which: int) -> int:
        return self._lib.jy_eng_dirty_count(self._h, which)

    def pend_count(self, which: int) -> int:
        return self._lib.jy_eng_pend_count(self._h, which)

    def export_dirty(self, which: int):
        cap = 256
        while True:
            rows = np.empty(cap, np.int64)
            op = np.empty(cap, np.uint64)
            on = np.empty(cap, np.uint64)
            sb = np.empty(cap, np.uint8)
            n = self._lib.jy_eng_export_dirty(
                self._h, which,
                rows.ctypes.data, op.ctypes.data, on.ctypes.data,
                sb.ctypes.data, cap,
            )
            if n >= 0:
                return rows[:n], op[:n], on[:n], sb[:n]
            cap = -n

    def own_set(self, which: int, row: int) -> int:
        """bit0 = P own ever written, bit1 = N own ever written."""
        return self._lib.jy_eng_own_set(self._h, which, row)

    # ---- the batch applier -------------------------------------------------

    def scan_apply(self, buf):
        """Apply a pipelined burst. Returns
        (rc, consumed, replies: bytes, unhandled: list[bytes] | None,
        changed_g, changed_pn); rc as documented in counter_engine.cpp."""
        if not buf:
            return 0, 0, b"", None, 0, 0
        base = ctypes.addressof(ctypes.c_char.from_buffer(buf))
        out_len = ctypes.c_int64()
        consumed = ctypes.c_int64()
        n_args = ctypes.c_int32()
        ch_g = ctypes.c_int32()
        ch_pn = ctypes.c_int32()
        rc = self._lib.jy_eng_scan_apply(
            self._h, ctypes.c_void_p(base), len(buf),
            self._out, _OUT_CAP, ctypes.byref(out_len),
            ctypes.byref(consumed),
            self._offs, self._lens, _MAX_ARGS, ctypes.byref(n_args),
            ctypes.byref(ch_g), ctypes.byref(ch_pn),
        )
        replies = ctypes.string_at(self._out, out_len.value)
        unhandled = None
        if rc == 1:
            view = memoryview(buf)
            unhandled = [
                bytes(view[self._offs[i] : self._offs[i] + self._lens[i]])
                for i in range(n_args.value)
            ]
            del view
        return rc, consumed.value, replies, unhandled, ch_g.value, ch_pn.value


def make_engine() -> CounterEngine | None:
    cdll = lib()
    return CounterEngine(cdll) if cdll is not None else None
