"""ctypes wrapper presenting the native RESP scanner behind the same
incremental-parser interface as server/resp.RespParser.

`make_parser()` returns a NativeRespParser when libjylis_native.so is
available, else the pure-Python RespParser — the server is agnostic.

The scanner reads straight out of the Python buffer via its address and
parses a whole pipelined burst per FFI call (resp_scan_many), so the
per-command cost is one C struct walk plus the unavoidable bytes-object
materialisation — not a ctypes round-trip per command.
"""

from __future__ import annotations

import ctypes
from collections import deque

from ..server.resp import RespError, RespParser
from . import lib

_MAX_CMDS = 256
_INITIAL_ARGS = 1024


class NativeRespParser:
    """Incremental RESP command parser over native resp_scan_many."""

    __slots__ = ("_buf", "_lib", "_ready", "_bad", "_argc", "_offs", "_lens", "_cap")

    def __init__(self, cdll):
        self._buf = bytearray()
        self._lib = cdll
        self._ready: deque[list[bytes]] = deque()
        self._bad = False  # protocol error after serving queued commands
        self._argc = (ctypes.c_int32 * _MAX_CMDS)()
        self._cap = _INITIAL_ARGS
        self._offs = (ctypes.c_int64 * self._cap)()
        self._lens = (ctypes.c_int64 * self._cap)()

    def append(self, data: bytes) -> None:
        self._buf += data

    def has_pending(self) -> bool:
        """Unconsumed bytes held (a split command's head): while true the
        stream's head belongs to this parser, not the native engine."""
        return bool(self._buf)

    def take_tail(self) -> bytes | None:
        """Hand the held bytes back to the caller (and forget them), so
        the stream's head can return to the native engine. Only legal
        when every parsed command has been iterated out and the stream
        is well-formed — returns None otherwise (the caller must then
        keep routing through this parser)."""
        if self._ready or self._bad:
            return None
        out = bytes(self._buf)
        del self._buf[:]
        return out

    def __iter__(self):
        return self

    def __next__(self) -> list[bytes]:
        if not self._ready:
            self._scan_burst()
        if self._ready:
            return self._ready.popleft()
        if self._bad:
            # the scanner stops with the malformed bytes at the buffer
            # head (resp_scan_many serves the prefix first); hand them to
            # the oracle parser so the error message — client-visible
            # bytes — matches the pure-Python serving path exactly
            oracle = RespParser()
            oracle.append(bytes(self._buf))
            for _ in oracle:  # raises the specific RespError
                pass
            raise RespError("protocol error")  # scanner/oracle disagree
        raise StopIteration

    def _scan_burst(self) -> None:
        while not self._bad:
            if not self._buf:
                return
            consumed = ctypes.c_int64()
            n_args = ctypes.c_int32()
            base = ctypes.addressof(ctypes.c_char.from_buffer(self._buf))
            rc = self._lib.resp_scan_many(
                ctypes.c_void_p(base), len(self._buf), ctypes.byref(consumed),
                self._argc, _MAX_CMDS,
                self._offs, self._lens, self._cap, ctypes.byref(n_args),
            )
            if rc == -2:  # grow the slice arrays and rescan
                self._cap = max(self._cap * 2, n_args.value)
                self._offs = (ctypes.c_int64 * self._cap)()
                self._lens = (ctypes.c_int64 * self._cap)()
                continue
            if rc == -1:
                self._bad = True
                return
            if rc == 0:
                return  # incomplete tail: wait for more input
            view = memoryview(self._buf)
            offs, lens, argc = self._offs, self._lens, self._argc
            a = 0
            for c in range(rc):
                n = argc[c]
                if n < 0:  # blank inline line: the oracle parser skips it
                    continue
                self._ready.append(
                    [bytes(view[offs[a + i] : offs[a + i] + lens[a + i]]) for i in range(n)]
                )
                a += n
            del view  # a live memoryview blocks bytearray resizing
            del self._buf[: consumed.value]
            if rc < _MAX_CMDS:
                return  # buffer exhausted of complete commands


def make_parser():
    cdll = lib()
    return NativeRespParser(cdll) if cdll is not None else RespParser()
