"""ctypes wrapper for the native MsgPushDeltas wire codec.

`encode_push(msg)` / `decode_push(body)` return None whenever the native
path can't (or shouldn't) handle the input — no library, UJSON payloads,
values outside u64, malformed bytes — and the caller falls back to the
pure-Python oracle in cluster/codec.py. For every input the native path
does accept, its output is byte-identical (encode) / object-equal (decode)
to the oracle; tests/test_native_codec.py fuzz-checks that equivalence.

The Python side does exactly one flattening pass over the delta objects
(list/ndarray building — C-speed per element); all varint/byte-shuffling
work happens in one or two FFI calls over contiguous buffers
(native/cluster_codec.cpp).
"""

from __future__ import annotations

import ctypes

import numpy as np

from ..cluster.msg import Msg, MsgPushDeltas
from . import lib

_U64_MAX = (1 << 64) - 1

# name -> ndicts for the counter family
_COUNTER_NDICTS = {"GCOUNT": 1, "PNCOUNT": 2}


def _ptr(arr: np.ndarray):
    return ctypes.c_void_p(arr.ctypes.data)


def _u64_array(values) -> np.ndarray | None:
    """Values as u64, or None if any falls outside [0, 2^64).

    Validation rides numpy's own dtype inference instead of a per-item
    Python isinstance/range scan (which dominated the whole encode): a
    list of in-range ints infers an integer dtype; anything else — a
    float, a bool, a negative mixed with >=2^63, an int past 2^64
    (object dtype) — infers a non-integer dtype and falls back to the
    oracle, which raises on genuinely invalid values rather than
    broadcasting a silently wrapped number to peers."""
    if not len(values):
        return np.empty(0, np.uint64)  # empty infers float64 below
    try:
        arr = np.asarray(values)
    except (OverflowError, TypeError, ValueError):
        return None
    if arr.dtype.kind == "u":
        return arr.astype(np.uint64, copy=False)
    if arr.dtype.kind == "i":
        if arr.size and int(arr.min()) < 0:
            return None
        return arr.astype(np.uint64)
    # mixed magnitudes (e.g. [1, 2**63]) infer float64 and ints past 2**64
    # infer object — exactly like genuine floats do, so only here pay the
    # per-item type scan, then let numpy's strict u64 conversion validate
    # the range (bools and floats fall back to the oracle)
    if all(type(v) is int and 0 <= v <= _U64_MAX for v in values):
        # explicit range check: numpy 1.x silently wraps out-of-range ints
        # on this conversion (pyproject now floors numpy>=2, but a wrapped
        # value broadcast to peers is bad enough to guard twice)
        try:
            return np.array(values, dtype=np.uint64)
        except (OverflowError, TypeError, ValueError):
            return None
    return None


def _key_blob(batch) -> tuple[bytes, np.ndarray, np.ndarray]:
    offs = np.empty(len(batch), np.int64)
    lens = np.empty(len(batch), np.int64)
    pos = 0
    parts = []
    for i, (key, _delta) in enumerate(batch):
        offs[i] = pos
        lens[i] = len(key)
        pos += len(key)
        parts.append(key)
    return b"".join(parts), offs, lens


# ---- encode ----------------------------------------------------------------


def encode_push(msg: MsgPushDeltas) -> bytes | None:
    cdll = lib()
    if cdll is None:
        return None
    name = msg.name
    if name in _COUNTER_NDICTS:
        return _encode_counters(cdll, msg, _COUNTER_NDICTS[name])
    if name == "TREG":
        return _encode_treg(cdll, msg)
    if name in ("TLOG", "SYSTEM"):
        return _encode_tlog(cdll, msg)
    if name == "UJSON":
        return _encode_ujson(cdll, msg)
    return None  # unknown: oracle


def _encode_counters(cdll, msg: MsgPushDeltas, ndicts: int) -> bytes | None:
    batch = msg.batch
    key_blob, key_off, key_len = _key_blob(batch)
    counts_l: list[int] = []
    rids: list[int] = []
    vals: list[int] = []
    # spans ship in dict-iteration order (keys()/values() extends are
    # C-speed); the native encoder sorts each span by rid on the wire —
    # the per-key sorted() this replaces dominated the whole encode
    for _key, delta in batch:
        dicts = (delta,) if ndicts == 1 else delta
        if len(dicts) != ndicts:
            return None
        for dct in dicts:
            counts_l.append(len(dct))
            # jlint: order-ok — spans ship in dict order on purpose (the
            # comment above); the NATIVE encoder sorts each span by rid
            # before emitting, byte-pinned against the sorting oracle by
            # tests/test_native_codec.py fuzz
            rids.extend(dct.keys())
            # jlint: order-ok — same: value order rides the rid sort
            vals.extend(dct.values())
    counts = np.asarray(counts_l, np.int64)
    rid_arr = _u64_array(rids)
    val_arr = _u64_array(vals)
    if rid_arr is None or val_arr is None:
        return None
    name_b = msg.name.encode()
    cap = (
        16 + len(name_b) + len(key_blob)
        + len(batch) * (10 + 10 * ndicts) + 20 * len(rids)
    )
    out = np.empty(cap, np.uint8)
    n = cdll.jy_push_counters_encode(
        name_b, len(name_b), len(batch),
        key_blob, _ptr(key_off), _ptr(key_len),
        ndicts, _ptr(counts), _ptr(rid_arr), _ptr(val_arr),
        _ptr(out), cap,
    )
    return out[:n].tobytes() if n >= 0 else None


def _encode_treg(cdll, msg: MsgPushDeltas) -> bytes | None:
    batch = msg.batch
    key_blob, key_off, key_len = _key_blob(batch)
    val_off = np.empty(len(batch), np.int64)
    val_len = np.empty(len(batch), np.int64)
    ts_list = []
    pos = 0
    parts = []
    for i, (_key, delta) in enumerate(batch):
        value, ts = delta
        val_off[i] = pos
        val_len[i] = len(value)
        pos += len(value)
        parts.append(value)
        ts_list.append(ts)
    ts_arr = _u64_array(ts_list)
    if ts_arr is None:
        return None
    val_blob = b"".join(parts)
    name_b = msg.name.encode()
    cap = 16 + len(name_b) + len(key_blob) + len(val_blob) + 30 * len(batch)
    out = np.empty(cap, np.uint8)
    n = cdll.jy_push_treg_encode(
        name_b, len(name_b), len(batch),
        key_blob, _ptr(key_off), _ptr(key_len),
        val_blob, _ptr(val_off), _ptr(val_len), _ptr(ts_arr),
        _ptr(out), cap,
    )
    return out[:n].tobytes() if n >= 0 else None


def _encode_tlog(cdll, msg: MsgPushDeltas) -> bytes | None:
    batch = msg.batch
    key_blob, key_off, key_len = _key_blob(batch)
    entry_counts = np.empty(len(batch), np.int64)
    cut_list = []
    ts_list: list[int] = []
    ent_parts: list[bytes] = []
    for i, (_key, delta) in enumerate(batch):
        entries, cutoff = delta
        entry_counts[i] = len(entries)
        cut_list.append(cutoff)
        for value, ts in entries:
            ent_parts.append(value)
            ts_list.append(ts)
    ts_arr = _u64_array(ts_list)
    cut_arr = _u64_array(cut_list)
    if ts_arr is None or cut_arr is None:
        return None
    ent_off = np.empty(len(ent_parts), np.int64)
    ent_len = np.empty(len(ent_parts), np.int64)
    pos = 0
    for i, part in enumerate(ent_parts):
        ent_off[i] = pos
        ent_len[i] = len(part)
        pos += len(part)
    ent_blob = b"".join(ent_parts)
    name_b = msg.name.encode()
    cap = (
        16 + len(name_b) + len(key_blob) + len(ent_blob)
        + 30 * len(batch) + 20 * len(ent_parts)
    )
    out = np.empty(cap, np.uint8)
    n = cdll.jy_push_tlog_encode(
        name_b, len(name_b), len(batch),
        key_blob, _ptr(key_off), _ptr(key_len),
        _ptr(entry_counts),
        ent_blob, _ptr(ent_off), _ptr(ent_len), _ptr(ts_arr),
        _ptr(cut_arr), _ptr(out), cap,
    )
    return out[:n].tobytes() if n >= 0 else None


def _encode_ujson(cdll, msg: MsgPushDeltas) -> bytes | None:
    """Flatten UJSON deltas in oracle order (entries by dot, vv by rid,
    cloud sorted; strings = path parts then token per entry) and varint-
    pack the whole batch in one FFI call."""
    batch = msg.batch
    key_blob, key_off, key_len = _key_blob(batch)
    counts = np.empty(len(batch) * 3, np.int64)
    ent_rid: list[int] = []
    ent_seq: list[int] = []
    path_counts: list[int] = []
    str_parts: list[bytes] = []
    vv_rid: list[int] = []
    vv_val: list[int] = []
    cl_rid: list[int] = []
    cl_seq: list[int] = []
    try:
        for i, (_key, u) in enumerate(batch):
            entries = u.entries
            counts[i * 3] = len(entries)
            for dot in sorted(entries):
                rid, seq = dot
                path, token = entries[dot]
                ent_rid.append(rid)
                ent_seq.append(seq)
                path_counts.append(len(path))
                for part in path:
                    str_parts.append(part.encode())
                str_parts.append(token.encode())
            vv = u.ctx.vv
            counts[i * 3 + 1] = len(vv)
            for rid in sorted(vv):
                vv_rid.append(rid)
                vv_val.append(vv[rid])
            cloud = sorted(u.ctx.cloud)
            counts[i * 3 + 2] = len(cloud)
            for rid, seq in cloud:
                cl_rid.append(rid)
                cl_seq.append(seq)
    except (AttributeError, TypeError):
        return None  # not host-lattice-shaped: oracle decides
    arrs = [
        _u64_array(ent_rid), _u64_array(ent_seq), _u64_array(vv_rid),
        _u64_array(vv_val), _u64_array(cl_rid), _u64_array(cl_seq),
    ]
    if any(a is None for a in arrs):
        return None
    er, es, vr, vvv, cr, cs = arrs
    pc = np.asarray(path_counts, np.int64) if path_counts else np.empty(0, np.int64)
    str_off = np.empty(len(str_parts), np.int64)
    str_len = np.empty(len(str_parts), np.int64)
    pos = 0
    for i, part in enumerate(str_parts):
        str_off[i] = pos
        str_len[i] = len(part)
        pos += len(part)
    str_blob = b"".join(str_parts)
    name_b = msg.name.encode()
    cap = (
        16 + len(name_b) + len(key_blob) + len(str_blob)
        + 40 * len(batch) + 30 * len(ent_rid) + 10 * len(str_parts)
        + 20 * (len(vv_rid) + len(cl_rid))
    )
    out = np.empty(cap, np.uint8)
    n = cdll.jy_push_ujson_encode(
        name_b, len(name_b), len(batch),
        key_blob, _ptr(key_off), _ptr(key_len),
        _ptr(counts), _ptr(er), _ptr(es), _ptr(pc),
        str_blob, _ptr(str_off), _ptr(str_len),
        _ptr(vr), _ptr(vvv), _ptr(cr), _ptr(cs),
        _ptr(out), cap,
    )
    return out[:n].tobytes() if n >= 0 else None


# ---- decode ----------------------------------------------------------------


def _read_header(body: bytes) -> tuple[str, int] | None:
    """Parse tag + name; return (name, offset-past-name) or None."""
    if not body or body[0] != 3:
        return None
    pos, shift, n = 1, 0, 0
    while True:
        if pos >= len(body) or shift > 70:
            return None
        b = body[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    if pos + n > len(body):
        return None
    try:
        name = body[pos : pos + n].decode()
    except UnicodeDecodeError:
        return None
    return name, pos + n


def decode_push(body: bytes) -> Msg | None:
    cdll = lib()
    if cdll is None:
        return None
    header = _read_header(body)
    if header is None:
        return None
    name, off = header
    rest = body[off:]
    if name in _COUNTER_NDICTS:
        return _decode_counters(cdll, name, rest, _COUNTER_NDICTS[name])
    if name == "TREG":
        return _decode_treg(cdll, name, rest)
    if name in ("TLOG", "SYSTEM"):
        return _decode_tlog(cdll, name, rest)
    if name == "UJSON":
        return _decode_ujson(cdll, name, rest)
    return None


class LazyU64Map:
    """A counter delta ({rid: u64}) decoded lazily from the wire arrays —
    the counter analog of ops/ujson_wire.WireUJSON: the wire decode
    banks list slices in O(1) per key and the dict materialises only
    when a consumer (converge's .items(), re-encode, equality) actually
    walks it. Compares equal to the real dict it denotes."""

    __slots__ = ("_rids", "_vals", "_lo", "_n", "_real")

    def __init__(self, rids, vals, lo, n):
        self._rids = rids
        self._vals = vals
        self._lo = lo
        self._n = n
        self._real = None

    def _mat(self) -> dict:
        real = self._real
        if real is None:
            lo = self._lo
            real = self._real = dict(
                zip(self._rids[lo : lo + self._n], self._vals[lo : lo + self._n])
            )
        return real

    def __eq__(self, other):
        if isinstance(other, LazyU64Map):
            other = other._mat()
        return self._mat() == other

    __hash__ = None  # mutable-mapping semantics, like dict

    def __len__(self) -> int:
        return self._n

    def __iter__(self):
        return iter(self._mat())

    def __getitem__(self, k):
        return self._mat()[k]

    def __contains__(self, k) -> bool:
        return k in self._mat()

    def get(self, k, default=None):
        return self._mat().get(k, default)

    def items(self):
        return self._mat().items()

    def keys(self):
        return self._mat().keys()

    def values(self):
        return self._mat().values()

    def __repr__(self) -> str:
        return repr(self._mat())


class LazyPNPair:
    """A PNCOUNT delta ((p_dict, n_dict)) decoded lazily from the wire
    arrays — one banked object per key instead of two maps plus a tuple,
    which matters because decode cost at this batch scale is dominated
    by Python allocation (each allocation tranche triggers gen-0 GC
    passes that walk every live JAX buffer). Compares equal to the real
    pair it denotes and unpacks like one."""

    __slots__ = ("_rids", "_vals", "_lo", "_np", "_nn", "_real")

    def __init__(self, rids, vals, lo, n_p, n_n):
        self._rids = rids
        self._vals = vals
        self._lo = lo
        self._np = n_p
        self._nn = n_n
        self._real = None

    def _mat(self) -> tuple:
        real = self._real
        if real is None:
            lo, mid = self._lo, self._lo + self._np
            real = self._real = (
                dict(zip(self._rids[lo:mid], self._vals[lo:mid])),
                dict(
                    zip(
                        self._rids[mid : mid + self._nn],
                        self._vals[mid : mid + self._nn],
                    )
                ),
            )
        return real

    def __eq__(self, other):
        if isinstance(other, LazyPNPair):
            other = other._mat()
        return self._mat() == other

    __hash__ = None

    def __len__(self) -> int:
        return 2

    def __iter__(self):
        return iter(self._mat())

    def __getitem__(self, i):
        return self._mat()[i]

    def __repr__(self) -> str:
        return repr(self._mat())


def _decode_counters(cdll, name, rest, ndicts) -> Msg | None:
    n_keys = ctypes.c_int64()
    total = ctypes.c_int64()
    rc = cdll.jy_push_counters_measure(
        rest, len(rest), ndicts, ctypes.byref(n_keys), ctypes.byref(total)
    )
    if rc != 0:
        return None
    nk, ne = n_keys.value, total.value
    key_off = np.empty(nk, np.int64)
    key_len = np.empty(nk, np.int64)
    counts = np.empty(nk * ndicts, np.int64)
    rids = np.empty(ne, np.uint64)
    vals = np.empty(ne, np.uint64)
    rc = cdll.jy_push_counters_decode(
        rest, len(rest), ndicts,
        _ptr(key_off), _ptr(key_len), _ptr(counts), _ptr(rids), _ptr(vals),
    )
    if rc != 0:
        return None
    rid_l = rids.tolist()
    val_l = vals.tolist()
    ko = key_off.tolist()
    kl = key_len.tolist()
    cl = counts.tolist()
    batch = []
    e = 0
    if ndicts == 1:
        for k in range(nk):
            c = cl[k]
            batch.append(
                (rest[ko[k] : ko[k] + kl[k]], LazyU64Map(rid_l, val_l, e, c))
            )
            e += c
    else:
        for k in range(nk):
            cp = cl[2 * k]
            cn = cl[2 * k + 1]
            batch.append(
                (
                    rest[ko[k] : ko[k] + kl[k]],
                    LazyPNPair(rid_l, val_l, e, cp, cn),
                )
            )
            e += cp + cn
    return MsgPushDeltas(name, tuple(batch))


def _decode_treg(cdll, name, rest) -> Msg | None:
    n_keys = ctypes.c_int64()
    rc = cdll.jy_push_treg_measure(rest, len(rest), ctypes.byref(n_keys))
    if rc != 0:
        return None
    nk = n_keys.value
    key_off = np.empty(nk, np.int64)
    key_len = np.empty(nk, np.int64)
    val_off = np.empty(nk, np.int64)
    val_len = np.empty(nk, np.int64)
    ts = np.empty(nk, np.uint64)
    rc = cdll.jy_push_treg_decode(
        rest, len(rest),
        _ptr(key_off), _ptr(key_len), _ptr(val_off), _ptr(val_len), _ptr(ts),
    )
    if rc != 0:
        return None
    ko, kl = key_off.tolist(), key_len.tolist()
    vo, vl = val_off.tolist(), val_len.tolist()
    tl = ts.tolist()
    batch = tuple(
        (rest[ko[k] : ko[k] + kl[k]], (rest[vo[k] : vo[k] + vl[k]], tl[k]))
        for k in range(nk)
    )
    return MsgPushDeltas(name, batch)


def _decode_ujson(cdll, name, rest) -> Msg | None:
    """Lazy receive path: one native pass splits the body into per-key
    WireUJSON payload spans (structure + utf-8 validated up front);
    documents materialise only if a host-lattice path touches them.
    Device-bound deltas go wire->planes without ever becoming dicts
    (ops/ujson_wire.grid_from_wire)."""
    from ..ops.ujson_wire import split_push_ujson

    batch = split_push_ujson(rest)
    if batch is None:
        return None
    return MsgPushDeltas(name, tuple(batch))


def _decode_tlog(cdll, name, rest) -> Msg | None:
    n_keys = ctypes.c_int64()
    total = ctypes.c_int64()
    rc = cdll.jy_push_tlog_measure(
        rest, len(rest), ctypes.byref(n_keys), ctypes.byref(total)
    )
    if rc != 0:
        return None
    nk, ne = n_keys.value, total.value
    key_off = np.empty(nk, np.int64)
    key_len = np.empty(nk, np.int64)
    entry_counts = np.empty(nk, np.int64)
    ent_off = np.empty(ne, np.int64)
    ent_len = np.empty(ne, np.int64)
    ent_ts = np.empty(ne, np.uint64)
    cutoffs = np.empty(nk, np.uint64)
    rc = cdll.jy_push_tlog_decode(
        rest, len(rest),
        _ptr(key_off), _ptr(key_len), _ptr(entry_counts),
        _ptr(ent_off), _ptr(ent_len), _ptr(ent_ts), _ptr(cutoffs),
    )
    if rc != 0:
        return None
    ko, kl = key_off.tolist(), key_len.tolist()
    cnt = entry_counts.tolist()
    eo, el = ent_off.tolist(), ent_len.tolist()
    et = ent_ts.tolist()
    cut = cutoffs.tolist()
    batch = []
    e = 0
    for k in range(nk):
        entries = [
            (rest[eo[i] : eo[i] + el[i]], et[i]) for i in range(e, e + cnt[k])
        ]
        e += cnt[k]
        batch.append((rest[ko[k] : ko[k] + kl[k]], (entries, cut[k])))
    return MsgPushDeltas(name, tuple(batch))
