"""Multi-lane serving: shard the node across host cores.

One asyncio loop plus the GIL is the hard ceiling behind the recorded
``vs_one_conn = 1.01`` (BENCH_full.json ``concurrent``: 64 connections
served no faster than one). This module runs a node as N serving
**lanes** — worker processes, each owning a complete serving stack
(ServeEngine, Database, journal segment, MetricsRegistry — the
per-Database registry refactor exists precisely so N databases coexist
cleanly) — sharing the RESP port via ``SO_REUSEPORT`` so the kernel
shards accepted connections across lanes with no userspace acceptor.

Convergence across lanes is the paper's own masterless-replica argument
applied across cores within one node: each lane is a delta-CRDT replica
(its OWN replica identity, derived from its bus address), and lanes
converge over a loopback **delta bus** that is literally the existing
cluster engine (``cluster/Cluster``) on ephemeral loopback ports — wire
framing, CRC, delta broadcast, digest-checked sync-on-rejoin, dial
backoff, all inherited. A command lands on whatever lane the kernel
picked; a key "owned" by another lane (``lane_of``) applies locally
(the client's ack never waits on a cross-lane hop) and the delta rides
the bus to every sibling, so reads serve-after-converge on any lane
within the proactive-flush cadence. CRDT join makes all of this
coordination-free: no lane ever blocks on another.

**One cluster identity.** Externally the node is still ONE member: lane
0 runs the ordinary external Cluster on ``config.addr`` alongside its
bus instance, and bridges the two meshes — database flushes tee to
both, inbound external deltas relay onto the bus, inbound lane deltas
relay out to external peers (converge never re-exports, so the relay
cannot echo). Remote nodes see one address and a digest-complete
replica; the lane topology is invisible on the wire.

**Durability.** Each lane journals the batches ITS serving path flushed
into its own segment (``journal.lane<k>.jylis``) — segments are
disjoint by acceptance and their union is the node's journaled state.
Boot replays all segments (merge replay; see ``journal.recover_all``
for the live-sibling safety rules) and lane-restart gaps heal over the
bus sync exactly like a node rejoining a cluster.

The **supervisor** (the ``--lanes N`` process) spawns and monitors the
lane workers, restarts crashed lanes with a bounded backoff, forwards
signals, records ``lanes.json`` (pids and ports — what the drill
matrix SIGKILLs), and — when ``--metrics-port`` is set — serves an
aggregated Prometheus endpoint that scrapes every lane, re-labels
samples with ``lane="k"``, and emits summed aggregate series for the
counter families.
"""

from __future__ import annotations

import asyncio
import json
import os
import re
import signal
import subprocess
import sys

from .obs import jtrace
from .obs.prom import MetricsHTTP
from .utils.address import Address, fnv1a64
from .utils.net import free_port

# env var: "<lane>:<failpoint spec>;<lane>:<spec>" — the supervisor
# merges each lane's spec into that CHILD's JYLIS_FAILPOINTS env (the
# drill matrix arms a crash in exactly one lane this way); the
# supervisor's own JYLIS_FAILPOINTS still propagates to every lane.
LANE_FAILPOINTS_ENV = "JYLIS_LANE_FAILPOINTS"

MANIFEST_NAME = "lanes.json"

# lane respawn backoff: first restart is quick (a drill kill should
# heal in ~a second), a crash-looping lane is bounded at the cap
RESTART_BACKOFF_S = 0.5
RESTART_BACKOFF_CAP_S = 10.0


def lane_of(key: bytes, n_lanes: int) -> int:
    """The lane whose keyspace slice ``key`` hashes into — stable
    FNV-1a, so every lane (and every client library that wants
    lane-affine connections) computes the same owner."""
    if n_lanes <= 1:
        return 0
    return fnv1a64(key) % n_lanes


def bus_address(config, lane_id: int) -> Address:
    """Lane ``lane_id``'s bus address: loopback, its assigned bus
    port, and a ``name#laneK`` suffix on the node's advertised name.
    Transport only — the lane's CRDT replica identity is
    ``lane_identity`` below, which must NOT involve the (ephemeral)
    bus port."""
    return Address(
        "127.0.0.1",
        str(config.lane_bus[lane_id]),
        f"{config.addr.name}#lane{lane_id}",
    )


def lane_identity(config, lane_id: int) -> int:
    """The lane's CRDT replica identity: the node's STABLE advertised
    address plus the lane ordinal. Every lane must be a distinct
    replica (two lanes sharing an identity would clobber each other's
    counter columns on converge), and the identity must be stable
    across restarts — deriving it from the ephemeral bus port would
    mint N brand-new replica ids per reboot, growing every counter's
    replica columns (and the wire/journal/device footprint) forever."""
    return Address(
        config.addr.host, config.addr.port,
        f"{config.addr.name}#lane{lane_id}",
    ).hash64()


def bus_config(config, lane_id: int):
    """The derived Config the lane's bus Cluster runs on: bus address,
    the sibling lanes as seeds, and the (fast) bus heartbeat."""
    from .utils.config import Config

    cfg = Config()
    cfg.port = config.port
    cfg.addr = bus_address(config, lane_id)
    cfg.seed_addrs = [
        bus_address(config, j)
        for j in range(config.lanes)
        if j != lane_id
    ]
    cfg.heartbeat_time = config.lane_bus_heartbeat
    cfg.system_log_trim = config.system_log_trim
    cfg.dial_timeout = config.dial_timeout
    cfg.dial_backoff_cap = config.dial_backoff_cap
    # the bus instance MINTS session tokens (it is the driving cluster
    # that binds the lane's SessionIndex), so it needs the boot-epoch
    # sidecar floor too: the supervisor reuses bus ports across lane
    # respawns, and without the floor a backwards clock step across a
    # respawn could re-mint a used epoch and alias the old stream
    # (review find). Across SUPERVISOR restarts the ports (and so the
    # rids) change anyway, which is safe by construction.
    cfg.data_dir = config.data_dir
    # the bus is where a lane's sequenced flushes originate, so the
    # operator's provenance sample rate must reach it (a fresh Config
    # would silently reset it to the default)
    cfg.trace_sample = config.trace_sample
    cfg.log = config.log
    return cfg


def snapshot_name(lane_id: int | None) -> str:
    if lane_id is None:
        return "snapshot.jylis"
    return f"snapshot.lane{lane_id}.jylis"


def list_snapshots(data_dir: str) -> list[str]:
    """Every snapshot file under any lane naming, sorted — boot restores
    all of them (restore is lattice convergence; overlap is a no-op)."""
    out = []
    for fname in sorted(os.listdir(data_dir)):
        if fname == "snapshot.jylis" or (
            fname.startswith("snapshot.lane") and fname.endswith(".jylis")
        ):
            out.append(os.path.join(data_dir, fname))
    return out


def wire_bridge(bus, external) -> None:
    """Lane 0's two-mesh bridge. The bus instance drives the one
    database flush and tees it to both meshes; each mesh relays the
    first-sight pushes it converged onto the other. Relay cannot echo:
    the session index's first-sight check dedupes per (origin, seq),
    and only lane 0 relays.

    Schema v10: relays preserve ORIGIN attribution (MsgRelayPush). The
    tee ships the lane's own flush into the external mesh under its bus
    rid + bus seq — so an external peer's applied vector tracks the
    exact stream a token minted on this lane references — and each
    mesh's converged sequenced pushes cross over with their origin
    rid/seq intact. Unsequenced sync data (origin None) still crosses
    as a plain broadcast: it advances no session watermark, but keeps
    rejoin heals flowing between the meshes at the old cadence."""

    def tee(deltas) -> None:
        origin, oseq = bus.broadcast_deltas(deltas)
        if origin is not None:
            # carry the bus flush's sampled span (schema v11) onto the
            # external leg: last_span is set synchronously by the
            # broadcast above, so the SAME chain crosses both meshes
            external.relay_deltas(origin, oseq, deltas, bus.last_span)
        else:
            # content-free keepalives: the broadcast path's own
            # unsequenced branch handles them
            external.broadcast_deltas(deltas)

    def relay_to(other):
        def relay(origin, oseq, name, batch, span=b"") -> None:
            if origin is not None:
                other.relay_deltas(origin, oseq, (name, batch), span)
            else:
                # relayed SYNC data (rejoin heals, range repairs):
                # UNSEQUENCED on purpose — re-originating it as
                # `other`'s own stream would consume own-content
                # ordinals that the far side of the bridge can never
                # observe, stranding tokens that reference them
                other.push_unsequenced((name, batch))

        return relay

    bus.flush_sink = tee
    bus.on_push = relay_to(external)
    external.on_push = relay_to(bus)
    # hop-tag the two legs so a chain reads origin -> bus -> cluster
    # (obs/jtrace.py): the bus instance's relays are the intra-node
    # lane fan-out, the external instance's are the WAN leg
    bus.relay_hop = jtrace.HOP_BUS
    external.relay_hop = jtrace.HOP_CLUSTER


class LaneClusters:
    """The lane worker's cluster handle for Dispose: one dispose() over
    the bus instance and (on lane 0) the external instance."""

    def __init__(self, *clusters):
        self.clusters = [c for c in clusters if c is not None]

    async def start(self) -> None:
        for c in self.clusters:
            await c.start()

    def dispose(self) -> None:
        for c in self.clusters:
            c.dispose()


# ---- the supervisor ---------------------------------------------------------


def _effective_jax_platform() -> str | None:
    """The PARENT's effective jax platform, for child env: a test
    parent that overrode the platform in-process (jax.config.update)
    has an os.environ that still names the real chip — children must
    inherit what the parent actually runs on."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return jax.config.jax_platforms
    except AttributeError:
        return None


def _parse_lane_failpoints(spec: str) -> dict[int, str]:
    out: dict[int, str] = {}
    for item in spec.split(";"):
        item = item.strip()
        if not item or ":" not in item:
            continue
        lane, fspec = item.split(":", 1)
        try:
            out[int(lane)] = fspec
        except ValueError:
            continue
    return out


class Supervisor:
    def __init__(self, config, argv: list[str] | None):
        self.config = config
        self.argv = list(argv or [])
        self.log = config.log
        self.n = config.lanes
        self.resp_port = int(config.port) or free_port()
        self.bus_ports = [free_port() for _ in range(self.n)]
        self.metrics_ports = (
            [free_port() for _ in range(self.n)]
            if config.metrics_port
            else [0] * self.n
        )
        self.procs: list[subprocess.Popen | None] = [None] * self.n
        self.restarts = [0] * self.n
        self._lane_failpoints = _parse_lane_failpoints(
            os.environ.get(LANE_FAILPOINTS_ENV, "")
        )
        self._shutdown = False
        self._manifest_lock = asyncio.Lock()
        self.done = asyncio.Event()

    # ---- spawning ---------------------------------------------------------

    def _child_argv(self, lane_id: int) -> list[str]:
        # later occurrences override earlier ones under argparse, so the
        # original argv rides along verbatim and the lane overrides
        # append — the child reparses the exact operator intent plus
        # the supervisor's resolved ports and the (possibly generated)
        # node name
        return [
            sys.executable, "-m", "jylis_tpu", *self.argv,
            "--lanes", str(self.n),
            "--lane-id", str(lane_id),
            "--lane-bus", ",".join(str(p) for p in self.bus_ports),
            "--port", str(self.resp_port),
            "--addr", str(self.config.addr),
            "--metrics-port", str(self.metrics_ports[lane_id]),
        ]

    def _child_env(self, lane_id: int) -> dict:
        env = dict(os.environ)
        plat = _effective_jax_platform()
        if plat:
            env["JAX_PLATFORMS"] = plat
        extra = self._lane_failpoints.get(lane_id)
        if extra:
            base = env.get("JYLIS_FAILPOINTS", "")
            env["JYLIS_FAILPOINTS"] = f"{base},{extra}" if base else extra
        return env

    def _spawn(self, lane_id: int) -> None:
        self.procs[lane_id] = subprocess.Popen(
            self._child_argv(lane_id), env=self._child_env(lane_id)
        )
        self.log.info() and self.log.i(
            f"lane {lane_id} pid {self.procs[lane_id].pid} "
            f"(bus :{self.bus_ports[lane_id]})"
        )

    def write_manifest(self) -> None:
        """``DIR/lanes.json``: who serves which lane right now — the
        drill matrix (and operators) SIGKILL by these pids."""
        if not self.config.data_dir:
            return
        manifest = {
            "port": self.resp_port,
            "metrics_port": self.config.metrics_port,
            "supervisor_pid": os.getpid(),
            "lanes": [
                {
                    "id": k,
                    "pid": p.pid if p is not None else None,
                    "bus_port": self.bus_ports[k],
                    "metrics_port": self.metrics_ports[k],
                }
                for k, p in enumerate(self.procs)
            ],
        }
        path = os.path.join(self.config.data_dir, MANIFEST_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f, indent=1)
        os.replace(tmp, path)

    async def write_manifest_async(self) -> None:
        """The supervisor-loop entry: the write-then-rename runs in a
        worker thread. The loop this method runs on carries every
        lane's death-watcher, signal handling, and the aggregated
        metrics endpoint — jlint's interprocedural JL101 caught the
        previous direct call: a contended disk during a crash-respawn
        storm stalled all three behind the manifest write. The lock
        restores what the on-loop call had implicitly: two lanes dying
        near-simultaneously must not interleave writes on the one
        fixed ``lanes.json.tmp`` path."""
        async with self._manifest_lock:
            await asyncio.to_thread(self.write_manifest)

    # ---- lifecycle --------------------------------------------------------

    async def run(self) -> None:
        if self.config.data_dir:
            # jlint: blocking-ok — startup, before any lane or client exists
            os.makedirs(self.config.data_dir, exist_ok=True)
        for k in range(self.n):
            self._spawn(k)
        await self.write_manifest_async()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, self._on_signal)
        aggregator = None
        if self.config.metrics_port:
            aggregator = LaneMetricsAggregator(
                max(self.config.metrics_port, 0), self.metrics_ports, self.log
            )
            await aggregator.start()
            self.log.info() and self.log.i(
                f"aggregated metrics endpoint on port: {aggregator.port}"
            )
        self.log.info() and self.log.i(
            f"serving {self.n} lanes on port: {self.resp_port}"
        )
        stop_waiter = asyncio.ensure_future(self.done.wait())
        waiters = {
            k: asyncio.ensure_future(self._wait_lane(k))
            for k in range(self.n)
        }
        try:
            while not self._shutdown:
                await asyncio.wait(
                    set(waiters.values()) | {stop_waiter},
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if self._shutdown:
                    break
                for k in list(waiters):
                    if waiters[k].done():
                        # backoff + respawn runs INSIDE the lane's own
                        # waiter chain: one crash-looping lane's 10 s
                        # backoff must not delay observing another
                        # lane's death (or a shutdown signal)
                        waiters[k] = asyncio.ensure_future(
                            self._respawn_then_wait(k)
                        )
        finally:
            stop_waiter.cancel()
            for t in waiters.values():
                t.cancel()
            if aggregator is not None:
                await aggregator.dispose()
            await self._stop_all()

    async def _wait_lane(self, lane_id: int) -> int:
        proc = self.procs[lane_id]
        assert proc is not None
        return await asyncio.to_thread(proc.wait)

    async def _respawn_then_wait(self, lane_id: int) -> int:
        await self._lane_died(lane_id)
        if self._shutdown:
            return 0
        return await self._wait_lane(lane_id)

    async def _lane_died(self, lane_id: int) -> None:
        proc = self.procs[lane_id]
        rc = proc.returncode if proc is not None else None
        if rc == 86 and lane_id in self._lane_failpoints:
            # faults.CRASH_EXIT_CODE: the lane died to ITS injected
            # failpoint. Env arming re-reads at import, so respawning
            # with the spec intact would re-arm it and crash-loop the
            # lane by construction — per-lane injected specs are
            # one-shot: the respawn comes up clean (the drill's heal).
            del self._lane_failpoints[lane_id]
            self.log.info() and self.log.i(
                f"lane {lane_id}: injected failpoint spec cleared after crash"
            )
        self.restarts[lane_id] += 1
        backoff = min(
            RESTART_BACKOFF_S * (2 ** (self.restarts[lane_id] - 1)),
            RESTART_BACKOFF_CAP_S,
        )
        self.log.warn() and self.log.w(
            f"lane {lane_id} died (rc {rc}); respawning in {backoff:.1f}s"
        )
        await asyncio.sleep(backoff)
        if self._shutdown:
            return
        self._spawn(lane_id)
        await self.write_manifest_async()

    def _on_signal(self) -> None:
        self._shutdown = True
        self.done.set()

    async def _stop_all(self) -> None:
        for proc in self.procs:
            if proc is not None and proc.poll() is None:
                try:
                    proc.terminate()
                except OSError:
                    pass
        for proc in self.procs:
            if proc is None:
                continue
            try:
                await asyncio.wait_for(asyncio.to_thread(proc.wait), 60.0)
            except asyncio.TimeoutError:
                proc.kill()
                await asyncio.to_thread(proc.wait)


async def run_supervisor(config, argv: list[str] | None) -> None:
    await Supervisor(config, argv).run()


# ---- aggregated Prometheus endpoint ----------------------------------------

# one exposition sample: name, optional {labels}, value
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (-?[0-9.eE+-]+)$"
)

# families whose samples are counters and therefore sum across lanes
# into the aggregate (no lane label) series; quantile summaries and
# gauges stay per-lane only — summing a p99 is not a p99. Cumulative
# histogram buckets (`_bucket`) SUM correctly by definition — that is
# the whole point of exporting them — so the aggregate scrape carries
# a real fleet-level histogram per seam.
_SUMMABLE = re.compile(
    r"(_total$|_count$|_sum$|_bucket$|^jylis_trace_events$)"
)

_SLO_OK_RE = re.compile(r'kind="ok_(\d+)"')


def aggregate_expositions(bodies: dict[int, str | None]) -> str:
    """Merge per-lane scrape bodies: every sample re-labeled with
    ``lane="k"``, counter families additionally summed into aggregate
    (lane-less) series, and a ``jylis_lane_up`` gauge per lane (0 for a
    lane whose scrape failed — mid-restart, typically)."""
    out: list[str] = []
    sums: dict[tuple[str, str], float] = {}
    meta_done: set[str] = set()
    for lane_id in sorted(bodies):
        body = bodies[lane_id]
        if body is None:
            continue
        for line in body.splitlines():
            if not line:
                continue
            if line.startswith("#"):
                # HELP/TYPE once, from the first live lane that has it
                key = " ".join(line.split()[:3])
                if key not in meta_done:
                    meta_done.add(key)
                    out.append(line)
                continue
            m = _SAMPLE_RE.match(line)
            if m is None:
                continue  # defensive: never re-emit an invalid line
            name, labels, value = m.group(1), m.group(2) or "", m.group(3)
            if labels:
                relabeled = f'{name}{{lane="{lane_id}",{labels[1:]}'
            else:
                relabeled = f'{name}{{lane="{lane_id}"}}'
            out.append(f"{relabeled} {value}")
            if _SUMMABLE.search(name):
                try:
                    sums[(name, labels)] = sums.get((name, labels), 0.0) + float(value)
                except ValueError:
                    pass
    for (name, labels), v in sorted(sums.items()):
        text = f"{v:.9f}".rstrip("0").rstrip(".") if "." in f"{v:.9f}" else str(v)
        out.append(f"{name}{labels} {text}")
    # fleet-level convergence SLO: the per-lane jylis_converge_slo
    # gauges are fractions (not summable), but their ok/sampled
    # NUMERATORS are counters we just summed — recompute the node-wide
    # fraction from the aggregate counts, which weights lanes by their
    # actual sample volume instead of averaging ratios
    sampled = sums.get(("jylis_converge_slo_total", '{kind="sampled"}'), 0.0)
    for (name, labels), v in sorted(sums.items()):
        if name != "jylis_converge_slo_total":
            continue
        m = _SLO_OK_RE.search(labels)
        if m is not None:
            frac = v / sampled if sampled > 0 else 0.0
            out.append(
                f'jylis_converge_slo{{le="{m.group(1)}"}} {frac:.6f}'
            )
    out.append("# TYPE jylis_lane_up gauge")
    for lane_id in sorted(bodies):
        up = 1 if bodies[lane_id] is not None else 0
        out.append(f'jylis_lane_up{{lane="{lane_id}"}} {up}')
    return "\n".join(out) + "\n"


class LaneMetricsAggregator(MetricsHTTP):
    """GET /metrics on the supervisor's port: scrape every lane's own
    endpoint, merge per ``aggregate_expositions``. A lane that fails to
    answer (crashed, restarting) shows up as ``jylis_lane_up 0`` rather
    than failing the whole scrape. The HTTP responder itself is
    obs/prom.py's MetricsHTTP with this class's render swapped in."""

    def __init__(self, port: int, lane_ports: list[int], log=None):
        super().__init__(None, port, log, render_async=self.render)
        self._lane_ports = lane_ports

    async def _fetch(self, port: int) -> str | None:
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection("127.0.0.1", port), 5.0
            )
        except (OSError, asyncio.TimeoutError):
            return None
        try:
            writer.write(
                b"GET /metrics HTTP/1.1\r\nHost: lane\r\n"
                b"Connection: close\r\n\r\n"
            )
            await writer.drain()
            raw = await asyncio.wait_for(reader.read(), 10.0)
        except (OSError, asyncio.TimeoutError):
            return None
        finally:
            writer.close()
        head, sep, body = raw.partition(b"\r\n\r\n")
        if not sep or b" 200 " not in head.split(b"\r\n", 1)[0]:
            return None
        return body.decode(errors="replace")

    async def render(self) -> str:
        bodies = dict(
            zip(
                range(len(self._lane_ports)),
                await asyncio.gather(
                    *(self._fetch(p) for p in self._lane_ports)
                ),
            )
        )
        return aggregate_expositions(bodies)
