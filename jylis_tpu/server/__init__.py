"""Client API layer: RESP (Redis protocol) codec and asyncio TCP server.

Reference analog: jylis/server.pony, server_notify.pony + the pony-resp
dependency (SURVEY.md section 2.4).
"""

from .resp import Respond, RespParser, RespError  # noqa: F401
