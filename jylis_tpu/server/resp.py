"""RESP (Redis Serialization Protocol) codec.

The rebuild's equivalent of the pony-resp dependency (reference:
server_notify.pony:33-36 feeds bytes to CommandParser; every repo replies
through Respond). Two halves:

* ``Respond`` — streaming reply writer over a byte sink. The sink
  indirection is the testability seam the reference relies on
  (test/test_cluster.pony:6-41 fakes it): the engine is drivable with no
  socket anywhere.
* ``RespParser`` — incremental command parser: RESP arrays of bulk strings
  (what real clients send) plus inline space-separated commands (what
  humans type into nc), yielding complete commands as lists of bytes.

Reply byte shapes are pinned by the reference's integration test
(test/test_cluster.pony:123-128: b"+OK\\r\\n", b":9\\r\\n") and the
docs (docs/_docs/start/connect.md: any Redis client is compatible).

A C++ fast-path parser (native/) slots in behind the same interface for
high-throughput ingestion; this pure-Python one is the always-available
fallback and the reference for its tests.
"""

from __future__ import annotations

CRLF = b"\r\n"


class RespError(Exception):
    """Protocol-level error: the connection should be dropped (reference:
    server_notify.pony:19-22 disposes the connection on parse errors)."""


class Respond:
    """Streaming RESP reply writer; ``sink`` receives encoded bytes."""

    __slots__ = ("_sink",)

    def __init__(self, sink):
        self._sink = sink

    def ok(self) -> None:
        self._sink(b"+OK" + CRLF)

    def simple(self, s: str) -> None:
        self._sink(b"+" + s.encode() + CRLF)

    def err(self, msg: str) -> None:
        self._sink(b"-" + msg.encode() + CRLF)

    def u64(self, n: int) -> None:
        self._sink(b":%d" % n)
        self._sink(CRLF)

    def i64(self, n: int) -> None:
        self._sink(b":%d" % n)
        self._sink(CRLF)

    def string(self, s) -> None:
        if isinstance(s, str):
            s = s.encode()
        self._sink(b"$%d" % len(s) + CRLF + s + CRLF)

    def null(self) -> None:
        self._sink(b"$-1" + CRLF)

    def array_start(self, n: int) -> None:
        self._sink(b"*%d" % n + CRLF)


class RespParser:
    """Incremental RESP command parser.

    Feed raw socket bytes with ``append``; iterate complete commands (each a
    ``list[bytes]``). Malformed protocol raises RespError. Handles both RESP
    arrays (``*N\\r\\n$len\\r\\n...``) and inline commands (plain text line,
    space-separated) like real Redis servers do.
    """

    _MAX_BULK = 512 * 1024 * 1024  # Redis's proto-max-bulk-len default

    def __init__(self):
        self._buf = bytearray()

    def append(self, data: bytes) -> None:
        self._buf += data

    def has_pending(self) -> bool:
        """Unconsumed bytes held (a split command's head): while true the
        stream's head belongs to this parser, not the native engine."""
        return bool(self._buf)

    def take_tail(self) -> bytes | None:
        """Hand the held bytes back to the caller (and forget them), so
        the stream's head can return to the native engine. Only legal
        once every complete command has been iterated out — for this
        parser, any time (``_buf`` then holds exactly the split tail)."""
        out = bytes(self._buf)
        self._buf.clear()
        return out

    def __iter__(self):
        return self

    def __next__(self) -> list[bytes]:
        cmd = self._try_parse()
        if cmd is None:
            raise StopIteration
        return cmd

    # -- internals ----------------------------------------------------------

    def _find_line(self, start: int):
        idx = self._buf.find(b"\r\n", start)
        if idx < 0:
            if len(self._buf) - start > 64 * 1024:
                raise RespError("protocol error: line too long")
            return None, start
        return bytes(self._buf[start:idx]), idx + 2

    def _try_parse(self):
        if not self._buf:
            return None
        if self._buf[0:1] != b"*":
            # inline command: one text line, split on whitespace
            line, pos = self._find_line(0)
            if line is None:
                return None
            del self._buf[:pos]
            parts = line.split()
            return parts if parts else self._try_parse()

        line, pos = self._find_line(0)
        if line is None:
            return None
        if not line[1:].isdigit():  # strict: no +, no whitespace (as native)
            raise RespError("protocol error: bad array header")
        n = int(line[1:])
        if n > 1024 * 1024:
            raise RespError("protocol error: bad array length")
        items: list[bytes] = []
        for _ in range(n):
            header, pos2 = self._find_line(pos)
            if header is None:
                return None
            if header[0:1] != b"$":
                raise RespError("protocol error: expected bulk string")
            if not header[1:].isdigit():  # strict, matching the native scanner
                raise RespError("protocol error: bad bulk length")
            blen = int(header[1:])
            if blen > self._MAX_BULK:
                raise RespError("protocol error: bad bulk length")
            if len(self._buf) < pos2 + blen + 2:
                return None
            body = bytes(self._buf[pos2 : pos2 + blen])
            if self._buf[pos2 + blen : pos2 + blen + 2] != b"\r\n":
                raise RespError("protocol error: bulk not terminated")
            items.append(body)
            pos = pos2 + blen + 2
        del self._buf[:pos]
        return items
