"""RESP TCP server: the client API endpoint.

Reference analog: server.pony + server_listen_notify.pony +
server_notify.pony — accept clients on config.port (default 6379, same as
Redis), feed their bytes through the incremental command parser, route
complete commands into Database.apply, and on protocol errors reply with an
error and drop the connection (server_notify.pony:19-22).

Concurrency model: the asyncio loop replaces the per-connection Pony
actors. Commands apply through Database.apply_async — device-bound work
runs in a worker thread under a per-repo lock (models/manager.py), so a
slow drain stalls neither other connections nor the heartbeat. Within one
connection commands complete strictly in order (RESP replies must match
request order), which each connection's sequential await provides.
"""

from __future__ import annotations

import asyncio

from ..models.database import Database
from ..native.resp import make_parser
from ..utils.net import ipv4_port
from .resp import Respond, RespError


class Server:
    def __init__(self, config, database: Database):
        self._config = config
        self._database = database
        self._log = config.log
        self._server: asyncio.base_events.Server | None = None

    async def start(self) -> None:
        try:
            self._server = await asyncio.start_server(
                self._handle_client, host=None, port=int(self._config.port)
            )
        except OSError as e:
            self._log.err() and self._log.e(f"server listen failed: {e}")
            raise
        self._log.info() and self._log.i("server listen ready")

    @property
    def port(self) -> int:
        assert self._server is not None
        return ipv4_port(self._server)

    async def _handle_client(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        parser = make_parser()  # native scanner when built, Python fallback
        resp = Respond(writer.write)
        try:
            while True:
                data = await reader.read(1 << 16)
                if not data:
                    break
                parser.append(data)
                try:
                    for cmd in parser:
                        await self._database.apply_async(resp, cmd)
                except RespError as e:
                    resp.err(str(e))
                    break
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError, asyncio.CancelledError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def dispose(self) -> None:
        """Stop listening (client connections wind down as they close —
        the reference has the same posture, server.pony:16-20)."""
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
