"""RESP TCP server: the client API endpoint.

Reference analog: server.pony + server_listen_notify.pony +
server_notify.pony — accept clients on config.port (default 6379, same as
Redis), feed their bytes through the incremental command parser, route
complete commands into Database.apply, and on protocol errors reply with an
error and drop the connection (server_notify.pony:19-22).

Concurrency model: the asyncio loop replaces the per-connection Pony
actors. Commands apply through Database.apply_async — device-bound work
runs in a worker thread under a per-repo lock (models/manager.py), so a
slow drain stalls neither other connections nor the heartbeat. Within one
connection commands complete strictly in order (RESP replies must match
request order), which each connection's sequential await provides.
"""

from __future__ import annotations

import asyncio
import time

from .. import admission as admission_mod
from .. import faults
from ..models.database import Database
from ..native.resp import make_parser
from ..utils.net import ipv4_port
from .resp import Respond, RespError


class Server:
    def __init__(self, config, database: Database):
        self._config = config
        self._database = database
        self._log = config.log
        self._server: asyncio.base_events.Server | None = None
        self._conns: set[asyncio.StreamWriter] = set()
        self._closing = False
        # dispatch-latency seams (obs/): one histogram per serving path —
        # a native burst (one engine scan_apply call settling many
        # commands) vs one Python-path dispatch (deferred, demoted, or
        # busy-routed command). Resolved once; the registry's `enabled`
        # flag is checked per record so bench.py's obs-off comparison
        # run skips the clock reads too.
        self._reg = database.metrics
        self._h_burst = self._reg.hist("server.native_burst")
        self._h_py = self._reg.hist("server.py_dispatch")
        # serving-pipeline profiler (obs/): per-stage timers across the
        # whole RESP path, so the socket tax bench.py can only report as
        # one ratio (socket_cost_frac) is attributable stage by stage.
        # Each record is gated on the registry's `enabled` flag at the
        # seam, and the dispatch stage REUSES the burst/py elapsed above
        # rather than reading the clock again — the native hot path pays
        # zero additional perf_counter calls for the profiler.
        self._h_accept = self._reg.hist("pipeline.accept")
        self._h_read = self._reg.hist("pipeline.read")
        self._h_parse = self._reg.hist("pipeline.parse")
        self._h_classify = self._reg.hist("pipeline.classify")
        self._h_dispatch = self._reg.hist("pipeline.dispatch")
        self._h_reply_write = self._reg.hist("pipeline.reply_write")

    async def start(self) -> None:
        try:
            if getattr(self._config, "lanes", 1) > 1:
                # multi-lane serving: every lane binds the SAME port
                # with SO_REUSEPORT and the kernel shards accepted
                # connections across the lane processes — no userspace
                # acceptor, no fd passing. IPv4-only in this mode (each
                # family would otherwise need its own shared socket).
                import socket as _socket

                sock = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
                sock.setsockopt(
                    _socket.SOL_SOCKET, _socket.SO_REUSEPORT, 1
                )
                sock.bind(("0.0.0.0", int(self._config.port)))
                self._server = await asyncio.start_server(
                    self._handle_client, sock=sock
                )
            else:
                self._server = await asyncio.start_server(
                    self._handle_client, host=None, port=int(self._config.port)
                )
        except OSError as e:
            self._log.err() and self._log.e(f"server listen failed: {e}")
            raise
        self._log.info() and self._log.i("server listen ready")

    @property
    def port(self) -> int:
        assert self._server is not None
        return ipv4_port(self._server)

    async def _handle_client(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        if self._closing:
            # accepted just before dispose: the close loop could not see
            # this writer yet, and wait_closed would wait on it forever
            writer.close()
            return
        reg = self._reg
        # pipeline.accept: one sample per connection, handler entry to
        # first read — the setup cost a new client pays before its first
        # command can even be parsed
        t_acc = time.perf_counter() if reg.enabled else 0.0
        # jlint: blocking-ok — lib() is memoised at boot (warmup builds
        # an auto-engine Database before serving starts), so this never
        # reaches the loader's listdir/compile path on the loop
        parser = make_parser()  # native scanner when built, Python fallback
        # Python-path replies buffer here and flush once per parsed batch
        # (bounded below): a reply per write() was one tiny TCP segment
        # per COMMAND, and a demoted connection's pipelined burst became
        # a per-segment wakeup storm — measured 30-40x under the native
        # path's batched writes on the same burst. The engine's replies
        # bypass this buffer (they arrive pre-batched); flush() runs
        # before every direct engine write, so cross-path reply order is
        # exactly command order.
        out = bytearray()
        resp = Respond(out.extend)

        def flush(bound: int = 0) -> None:
            if len(out) > bound:
                t_w = time.perf_counter() if reg.enabled else 0.0
                writer.write(bytes(out))
                if t_w:
                    self._h_reply_write.record(time.perf_counter() - t_w)
                out.clear()

        engine = getattr(self._database, "native_engine", None)
        use_native = engine is not None
        buf = bytearray()
        self._conns.add(writer)
        try:
            adm_armed = self._database.admission.armed
            if t_acc:
                self._h_accept.record(time.perf_counter() - t_acc)
            while True:
                # pipeline.read: one socket read await. Deliberately
                # includes client idle time — under saturation this IS
                # the kernel-queue wait, and an idle connection's long
                # reads land in the top buckets where windowed quantiles
                # (SYSTEM LATENCY WINDOW) can separate them from load.
                t_rd = time.perf_counter() if reg.enabled else 0.0
                data = await reader.read(1 << 16)
                if t_rd:
                    self._h_read.record(time.perf_counter() - t_rd)
                if not data:
                    break
                # the overload signal's arrival stamp: queue time for
                # every command in this chunk runs from this read
                t_arr = time.perf_counter() if adm_armed else 0.0
                if use_native:
                    go_native = not any(
                        m.busy() for m in self._engine_managers()
                    )
                    if go_native and parser.has_pending():
                        # a previous burst was routed through the Python
                        # parser and left a split command's head behind:
                        # reclaim it so the stream returns to the engine.
                        # Without this, one mid-command chunk boundary
                        # (near-certain once a saturated connection fills
                        # 64 KiB reads) exiles the connection to the
                        # per-command Python path for as long as the
                        # backlog lasts — the engine abandoned exactly
                        # when its throughput matters most.
                        tail = parser.take_tail()
                        if tail is None:
                            go_native = False  # malformed/unserved: stay
                        else:
                            buf += tail
                    if not go_native:
                        # a drain holds a counter lock: route THIS burst
                        # through the per-repo Python path so unrelated
                        # repos never wait on the engine's two-lock
                        # boundary
                        parser.append(bytes(buf))
                        buf.clear()
                    else:
                        buf += data
                        use_native = await self._apply_native(
                            engine, buf, parser, resp, flush, writer, out,
                            t_arr,
                        )
                        if use_native:
                            flush()
                            await writer.drain()
                            continue
                        data = b""  # demoted: tail already moved into parser
                parser.append(data)
                try:
                    # pipeline.parse: manual next() so each Python-path
                    # command parse is timed individually; RespError
                    # still propagates to the handler below exactly as
                    # the for-loop form raised it
                    it = iter(parser)
                    while True:
                        t_ps = time.perf_counter() if reg.enabled else 0.0
                        cmd = next(it, None)
                        if t_ps:
                            self._h_parse.record(time.perf_counter() - t_ps)
                        if cmd is None:
                            break
                        await self._dispatch_py(resp, cmd, writer, out, t_arr)
                        flush(1 << 16)  # bound the reply buffer mid-burst
                except RespError as e:
                    resp.err(str(e))
                    flush()
                    break
                flush()
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError, asyncio.CancelledError):
            pass
        finally:
            self._database.admission.drop_conn(id(writer))
            self._conns.discard(writer)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def _dispatch_py(self, resp, cmd, writer, out, t_arr=0.0) -> None:
        """ONE Python-path dispatch (demoted loop and the native path's
        deferred commands share it): the overload-armor admission gate
        (admission.py) in front of Database.apply_async. When armed it
        classifies the command (SESSION WRAP/READ inherit their inner
        command's class), refreshes this connection's queued-reply-bytes
        accounting, and either refuses up front with a typed BUSY
        (retry-after hint included, before any session flush / repo
        lock / drain is paid for) or dispatches and feeds the overload
        state machine. Unarmed costs two attribute reads.

        ``t_arr`` is the perf_counter stamp of the socket read that
        delivered this command's chunk. The latency fed to the state
        machine runs from THERE, not from dispatch start: under an open
        loop the queueing delay lives in the connection's parsed-burst
        backlog (a 64 KiB chunk is thousands of commands drained
        sequentially), and a service-time-only EWMA sits flat at
        sub-millisecond while clients wait seconds — the signal must
        see time-in-our-own-queue or the node never declares overload."""
        adm = self._database.admission
        if adm.armed:
            adm.note_conn_queued(
                id(writer),
                writer.transport.get_write_buffer_size() + len(out),
            )
            # pipeline.classify: the admission toll per command on an
            # armed node — classify plus the gate's token walk, timed
            # for refusals and admissions alike
            t_cl = time.perf_counter() if self._reg.enabled else 0.0
            cls = admission_mod.classify(cmd)
            hint = await admission_mod.gate(adm, cls)
            if t_cl:
                self._h_classify.record(time.perf_counter() - t_cl)
            if hint is not None:
                resp.err(
                    admission_mod.busy_reply(
                        cls, hint, "node is shedding this class"
                    )
                )
                # the refusal path's ONLY await: without it a
                # backlogged chunk of thousands of shed commands runs
                # as one synchronous slab, and every OTHER connection's
                # (protected, admitted) commands stall behind it —
                # measured as ~300ms protected-read tails at 4x offered
                # load while the shed itself took microseconds
                await asyncio.sleep(0)
                return
            t0 = time.perf_counter()
            await self._database.apply_async(resp, cmd)
            t1 = time.perf_counter()
            adm.done(cls, t1 - (t_arr or t0))
            if self._reg.enabled:
                self._h_py.record(t1 - t0)
                self._h_dispatch.record(t1 - t0)
            return
        t0 = time.perf_counter() if self._reg.enabled else 0.0
        await self._database.apply_async(resp, cmd)
        if t0:
            el = time.perf_counter() - t0
            self._h_py.record(el)
            self._h_dispatch.record(el)

    # the engine's changed-counter order (serve_engine.cpp scan_apply2)
    _ENGINE_TYPES = ("GCOUNT", "PNCOUNT", "TREG", "TLOG", "UJSON")

    def _engine_managers(self):
        return [self._database.manager(n) for n in self._ENGINE_TYPES]

    async def _apply_native(
        self, engine, buf, parser, resp, flush, writer, out, t_arr=0.0
    ):
        """Drain `buf` through the native serving engine; commands it
        can't settle route through the normal per-repo async path in
        order (`resp` buffers those replies; `flush` pushes them to the
        writer before the engine's next direct write so the reply stream
        stays in command order). Returns True (stay native) or False
        (demote this connection to the Python path; tail moved into
        `parser` — on malformed input the Python parser then renders its
        specific error and the connection drops)."""
        mgrs = self._engine_managers()

        def demote() -> bool:
            # the whole connection moves to the Python dispatch path for
            # its remaining lifetime — counted so the live fallback_frac
            # (SYSTEM METRICS SERVING lines) reflects demotion events,
            # and traced so SYSTEM TRACE shows when/why serving slowed
            self._reg.note_serving("demotions")
            self._reg.trace_event("server", "demote")
            parser.append(bytes(buf))
            buf.clear()
            return False

        while True:
            if any(m._shutdown for m in mgrs):
                return demote()
            # all five type tables can mutate inside one native call: hold
            # every engine-backed repo lock, exactly the boundary
            # apply_async enforces per repo — a threaded drain holding any
            # one of them keeps the engine out entirely. Acquisition
            # follows the DATABASE MAP order (TREG, TLOG, G, PN, UJSON),
            # the same order database.all_locks uses, so the shutdown
            # snapshot can never deadlock against a serving burst.
            async with mgrs[2]._lock, mgrs[3]._lock, mgrs[0]._lock, \
                    mgrs[1]._lock, mgrs[4]._lock:
                try:
                    # native.scan_apply: a failure AT the FFI burst
                    # boundary must demote this connection to the Python
                    # oracle path (replies stay correct, at the measured
                    # demotion cliff), never kill the connection. The
                    # ASYNC point: an injected sleep must simulate a slow
                    # burst for THIS connection — the sync point's
                    # time.sleep stalled the whole loop (heartbeats and
                    # Pongs included), turning the drill into a node-wide
                    # freeze that idle-evicts our peer connections
                    # (caught by jlint's interprocedural JL101)
                    await faults.async_point("native.scan_apply")
                    t0 = time.perf_counter() if self._reg.enabled else 0.0
                    rc, consumed, replies, unhandled, changed = (
                        engine.scan_apply(buf)
                    )
                    if t0:
                        # pipeline.dispatch reuses the burst elapsed —
                        # one engine call settles the whole burst and
                        # the profiler must not add clock reads here
                        el = time.perf_counter() - t0
                        self._h_burst.record(el)
                        self._h_dispatch.record(el)
                except faults.FaultError:
                    return demote()
                if replies:
                    flush()  # deferred-command replies precede these
                    t_w = time.perf_counter() if self._reg.enabled else 0.0
                    writer.write(replies)
                    if t_w:
                        self._h_reply_write.record(
                            time.perf_counter() - t_w
                        )
                for mgr, ch in zip(mgrs, changed):
                    if ch:
                        mgr._maybe_proactive_flush()
            del buf[:consumed]
            # slow-consumer hard bound (--admission-queue-bytes): engine
            # replies land straight in the transport buffer; once the
            # node-wide queued total is past the cap, drain() here is
            # real per-connection backpressure — it parks only THIS
            # connection until its consumer catches up, outside the
            # repo locks, so the loop's memory stays bounded without
            # slowing healthy consumers
            adm = self._database.admission
            if adm.queue_bytes_cap:
                adm.note_conn_queued(
                    id(writer), writer.transport.get_write_buffer_size()
                )
                if adm.queued_bytes > adm.queue_bytes_cap:
                    await writer.drain()
                    adm.note_conn_queued(
                        id(writer),
                        writer.transport.get_write_buffer_size(),
                    )
            if rc == 1:  # one command for the Python path, in order
                await self._dispatch_py(resp, unhandled, writer, out, t_arr)
                # a burst of repeatedly deferring reads (e.g. renders
                # too big for the engine's reply buffer) produces no
                # engine write to piggyback on: bound the buffer here
                # exactly like the demoted loop does
                flush(1 << 16)
                continue
            if rc == 2:  # reply buffer flushed; keep going
                continue
            if rc < 0:
                # rc -1: malformed input — the Python parser (the oracle)
                # renders its specific error message so both serving paths
                # stay byte-identical on protocol errors, then drops the
                # connection. rc -2: oversized command — Python handles
                # this connection from here on.
                return demote()
            return True  # rc == 0: consumed all complete commands

    async def dispose(self) -> None:
        """Stop listening and close client connections (the reference
        stops its listener and lets process exit end connections,
        server.pony:16-20; Python 3.12's wait_closed would otherwise
        block shutdown until every idle client hung up on its own)."""
        self._closing = True  # handlers not yet in _conns self-close
        if self._server is not None:
            self._server.close()
            for w in list(self._conns):
                w.close()
            await self._server.wait_closed()
