"""Device-mesh parallelism for the CRDT keyspaces.

The scaling design (SURVEY.md §5.8, §7.6): the keyspace tensor is sharded
over a ``keys`` mesh axis — anti-entropy merge is embarrassingly parallel
over keys, so convergence needs ZERO collectives once delta batches are
routed to their shard. The lattice-join *collective* appears when full
states arrive sharded over a ``rep`` (replica) axis: a join semilattice's
all-reduce is ``lax.pmax`` over ICI — the CRDT analog of gradient psum.

Inter-node (DCN) communication stays on the host cluster layer (gossip +
delta push, jylis_tpu/cluster/) — collectives are the wrong tool for
elastic membership; the mesh handles the dense intra-pod math.
"""

from .mesh import make_mesh, serving_mesh
from .sharded import (
    converge_sharded,
    drain_sharded_g,
    drain_sharded_pn,
    drain_sharded_tlog,
    drain_sharded_treg,
    join_replica_axis,
    patch_sharded_treg,
    read_all_sharded,
    route_batch,
    route_drain,
    route_drain64,
    shard_docbatch,
    shard_plane,
    shard_vec,
)

__all__ = [
    "make_mesh",
    "serving_mesh",
    "shard_docbatch",
    "shard_plane",
    "shard_vec",
    "route_batch",
    "route_drain",
    "converge_sharded",
    "drain_sharded_g",
    "drain_sharded_pn",
    "drain_sharded_treg",
    "patch_sharded_treg",
    "drain_sharded_tlog",
    "route_drain64",
    "read_all_sharded",
    "join_replica_axis",
]
