"""Mesh construction helpers.

One place decides how devices become a `jax.sharding.Mesh`, so tests (8
virtual CPU devices), the driver's dryrun (N virtual devices), and real
TPU pods all build meshes the same way.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    n_devices: int | None = None, rep: int = 1, axis_names=("rep", "keys")
) -> Mesh:
    """A (rep × keys) mesh over the first ``n_devices`` devices.

    ``rep=1`` (the default) gives a pure keys-sharded mesh — the serving
    layout, where anti-entropy needs no collectives. ``rep>1`` carves a
    replica fan-in axis for `join_replica_axis` (the pmax join collective).
    """
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    if n_devices > len(devs):
        raise ValueError(f"need {n_devices} devices, have {len(devs)}")
    if n_devices % rep != 0:
        raise ValueError(f"n_devices {n_devices} not divisible by rep {rep}")
    grid = np.array(devs[:n_devices]).reshape(rep, n_devices // rep)
    return Mesh(grid, axis_names)


_SERVING_MESH: list = []  # memo cell: [Mesh | None] once resolved


def serving_mesh() -> Mesh | None:
    """The process-wide keys-sharded serving mesh, or None single-device.

    Repos call this at construction (mesh="auto"): with one visible device
    (the real tunneled TPU chip) they keep the single-chip fast path; with
    a multi-device platform (a pod slice, or the 8-virtual-device test
    harness) every counter keyspace is born keys-sharded across all of it.
    Memoised: jits specialise on the mesh as a static arg, so all repos
    must share one Mesh object.
    """
    if not _SERVING_MESH:
        # local devices, deliberately: a jylis node is one process on one
        # host, and its mesh is that host's chips. Spanning hosts inside
        # one node would make every drain a multi-controller SPMD program
        # — the wrong tool for an event-driven server. Cross-host scale is
        # the CLUSTER layer's job (gossip over DCN), same as the
        # reference's one-process-one-node model. See parallel/PLAN.md.
        n = len(jax.local_devices())
        _SERVING_MESH.append(make_mesh(n) if n > 1 else None)
    return _SERVING_MESH[0]
