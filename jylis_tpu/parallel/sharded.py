"""Key-sharded counter keyspaces: shard_map merge kernels + join collective.

The north-star path (BASELINE.json): PNCOUNT/GCOUNT anti-entropy over a
(keys × replicas) u64 tensor — stored as hi/lo u32 planes (ops/planes.py;
XLA's u64 emulation is 4-25x slower on scatters/reduces) — scaled over a
device mesh:

* **State layout:** each plane sharded ``P("keys", None)`` — a device owns
  a contiguous block of key rows with all replica columns resident, so
  both the join composite and the row-sum read are LOCAL.
* **Routing:** the host assigns key rows blockwise to shards
  (``row // rows_per_shard``); `route_batch` coalesces duplicate keys
  (max-combine — the join composite needs unique rows), buckets per shard,
  and pads to a common width, producing arrays whose leading axis is
  sharded over ``keys``. This is the host-side analog of the reference's
  per-type actor mailbox (repo_manager.pony:92-93) — batching is where the
  reference's per-key loop became one device launch.
* **Merge:** inside `shard_map`, each device runs the same gather ->
  joint-max -> scatter-set composite as the single-chip kernel on its
  block — ZERO collectives on the serving path; the mesh scales
  merges/sec linearly with chips.
* **Join collective:** when full per-replica states arrive sharded over a
  ``rep`` mesh axis (synthetic replicas spread over chips), the lattice
  join across that axis is a local fold + a two-phase u32 pmax (hi plane
  first, then the lo plane masked to hi-winners) — a max-all-reduce over
  ICI, the CRDT analog of data-parallel gradient psum.

All functions are pure and jit/shard_map-composable; dynamic work arrives
pre-padded (static shapes keep XLA's tiling friendly and the jit cache
small).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.base import pad_rows
from ..ops import planes

U32 = jnp.uint32


def shard_plane(mesh, arr):
    """Place one (K, ...) plane keys-sharded on the mesh. K must divide
    evenly by the keys axis (pad capacity with zeros — the lattice
    identity — before calling)."""
    return jax.device_put(arr, NamedSharding(mesh, P("keys", None)))


def route_batch(key_idx, deltas, n_shards: int, rows_per_shard: int):
    """Host-side shard routing: global (B,) rows + (B, R) u64 deltas become
    ((n_shards * W,) local rows, hi/lo (n_shards * W, R) u32 planes) with
    the leading axis blockwise-sharded; W is the padded per-shard width.
    Duplicate keys are max-combined here (the device composite requires
    unique rows); padded slots carry PAD_ROW, which the scatter drops.
    """
    key_idx, deltas = planes.coalesce(key_idx, deltas)
    shard_of = key_idx // rows_per_shard
    order = np.argsort(shard_of, kind="stable")
    counts = np.bincount(shard_of, minlength=n_shards)
    width = max(int(counts.max()) if len(key_idx) else 0, 1)
    # distinct out-of-range pads per shard: each device's scatter keeps an
    # honestly-unique index vector
    local_rows = np.broadcast_to(pad_rows(width), (n_shards, width)).copy()
    local_deltas = np.zeros((n_shards, width, deltas.shape[-1]), np.uint64)
    start = 0
    for s in range(n_shards):
        c = int(counts[s])
        sel = order[start : start + c]
        local_rows[s, :c] = key_idx[sel] % rows_per_shard
        local_deltas[s, :c] = deltas[sel]
        start += c
    d_hi, d_lo = planes.split64_np(
        local_deltas.reshape(n_shards * width, deltas.shape[-1])
    )
    return local_rows.reshape(n_shards * width), d_hi, d_lo


def _local_converge(hi_blk, lo_blk, rows_blk, dhi_blk, dlo_blk):
    """Per-shard join composite (same kernel as ops/gcount.converge_batch,
    applied to this device's key block)."""
    return planes.scatter_join(hi_blk, lo_blk, rows_blk, dhi_blk, dlo_blk)


# jit hoisted to module level with the mesh static: rebuilding the
# jit(shard_map) wrapper per call would retrace and recompile every merge
@partial(jax.jit, static_argnames=("mesh",), donate_argnums=(1, 2))
def _converge_sharded(mesh, hi, lo, local_rows, d_hi, d_lo):
    return jax.shard_map(
        _local_converge,
        mesh=mesh,
        in_specs=(
            P("keys", None),
            P("keys", None),
            P("keys"),
            P("keys", None),
            P("keys", None),
        ),
        out_specs=(P("keys", None), P("keys", None)),
    )(hi, lo, local_rows, d_hi, d_lo)


def converge_sharded(mesh, hi, lo, local_rows, d_hi, d_lo):
    """One anti-entropy merge step over the mesh: every device joins its
    routed slice into its key block. No communication."""
    return _converge_sharded(mesh, hi, lo, local_rows, d_hi, d_lo)


@partial(jax.jit, static_argnames=("mesh",))
def _read_all_sharded(mesh, hi, lo):
    return jax.shard_map(
        planes.rowsum64,
        mesh=mesh,
        in_specs=(P("keys", None), P("keys", None)),
        out_specs=P("keys"),
    )(hi, lo)


def read_all_sharded(mesh, hi, lo):
    """Row sums (counter values, u64 wrapping) for the whole keyspace;
    output stays keys-sharded — only materialise on host what you need."""
    return _read_all_sharded(mesh, hi, lo)


def _tree_join(hi_blk, lo_blk):
    """Log-depth joint fold over the leading axis."""
    while hi_blk.shape[0] > 1:
        s = hi_blk.shape[0]
        half = s // 2
        fhi, flo = planes.join_max(
            hi_blk[:half], lo_blk[:half], hi_blk[half : 2 * half], lo_blk[half : 2 * half]
        )
        if s % 2:  # odd leftover rides along
            fhi = jnp.concatenate([fhi, hi_blk[-1:]])
            flo = jnp.concatenate([flo, lo_blk[-1:]])
        hi_blk, lo_blk = fhi, flo
    return hi_blk, lo_blk


def _local_then_pmax(hi_blk, lo_blk):
    # fold the shard's own replica rows jointly first (pmax alone only
    # joins row-for-row across devices), then two-phase u32 all-reduce:
    # hi decides; lo competes only where hi is the winner
    fhi, flo = _tree_join(hi_blk, lo_blk)
    jhi = jax.lax.pmax(fhi, "rep")
    lo_cand = jnp.where(fhi == jhi, flo, jnp.uint32(0))
    jlo = jax.lax.pmax(lo_cand, "rep")
    return (
        jnp.broadcast_to(jhi, hi_blk.shape),
        jnp.broadcast_to(jlo, lo_blk.shape),
    )


@partial(jax.jit, static_argnames=("mesh",))
def _pmax_join(mesh, hi, lo):
    return jax.shard_map(
        _local_then_pmax,
        mesh=mesh,
        in_specs=(P("rep", "keys"), P("rep", "keys")),
        out_specs=(P("rep", "keys"), P("rep", "keys")),
    )(hi, lo)


def join_replica_axis(mesh, hi_stacked, lo_stacked):
    """Lattice-join full states sharded over the ``rep`` mesh axis.

    hi/lo_stacked: (S, K) u32 planes sharded P("rep", "keys") — S
    per-replica full u64 states. Afterwards every row of every rep-shard
    holds the converged state.
    """
    return _pmax_join(mesh, hi_stacked, lo_stacked)
