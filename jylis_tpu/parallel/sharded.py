"""Key-sharded counter keyspaces: shard_map merge kernels + join collective.

The north-star path (BASELINE.json): PNCOUNT/GCOUNT anti-entropy over a
(keys × replicas) uint64 tensor, scaled over a device mesh:

* **State layout:** ``counts[key, replica]`` sharded ``P("keys", None)`` —
  each device owns a contiguous block of key rows with all replica columns
  resident, so both the scatter-max join and the row-sum read are LOCAL.
* **Routing:** the host assigns key rows round-robin-by-block to shards
  (``row // rows_per_shard``); `route_batch` buckets a delta batch per
  shard and pads to a common width, producing arrays whose leading axis is
  sharded over ``keys``. This is the host-side analog of the reference's
  per-type actor mailbox (repo_manager.pony:92-93) — batching is where the
  reference's per-key loop became one device launch.
* **Merge:** inside `shard_map`, each device runs the same scatter-max as
  the single-chip kernel on its block — ZERO collectives on the serving
  path; the mesh scales merges/sec linearly with chips.
* **Join collective:** when full per-replica states arrive sharded over a
  ``rep`` mesh axis (64 synthetic replicas spread over chips), the lattice
  join across that axis is ``lax.pmax`` — a max-all-reduce over ICI, the
  CRDT analog of data-parallel gradient psum (`join_replica_axis`).

All functions are pure and jit/shard_map-composable; dynamic work arrives
pre-padded (static shapes keep XLA's tiling on the MXU-friendly layouts
and the jit cache small).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.base import PAD_ROW

UINT64 = jnp.uint64


def shard_counts(mesh, counts):
    """Place a (K, R) counts tensor keys-sharded on the mesh. K must divide
    evenly by the keys axis (pad capacity with zeros — the lattice
    identity — before calling)."""
    return jax.device_put(counts, NamedSharding(mesh, P("keys", None)))


def route_batch(key_idx, deltas, n_shards: int, rows_per_shard: int):
    """Host-side shard routing: global (B,) rows + (B, R) deltas become
    ((n_shards * W,) local rows, (n_shards * W, R) deltas) with the leading
    axis blockwise-sharded; W is the padded per-shard width. Padded slots
    carry PAD_ROW, which the scatter drops (mode="drop").

    Duplicate keys inside one batch are fine: max is the combiner.
    """
    key_idx = np.asarray(key_idx)
    deltas = np.asarray(deltas)
    shard_of = key_idx // rows_per_shard
    order = np.argsort(shard_of, kind="stable")
    counts = np.bincount(shard_of, minlength=n_shards)
    width = max(int(counts.max()) if len(key_idx) else 0, 1)
    local_rows = np.full((n_shards, width), PAD_ROW, np.int32)
    local_deltas = np.zeros((n_shards, width, deltas.shape[-1]), deltas.dtype)
    start = 0
    for s in range(n_shards):
        c = int(counts[s])
        sel = order[start : start + c]
        local_rows[s, :c] = key_idx[sel] % rows_per_shard
        local_deltas[s, :c] = deltas[sel]
        start += c
    return (
        local_rows.reshape(n_shards * width),
        local_deltas.reshape(n_shards * width, deltas.shape[-1]),
    )


def _local_converge(counts_blk, rows_blk, deltas_blk):
    """Per-shard scatter-max (same kernel as ops/gcount.converge_batch,
    applied to this device's key block)."""
    return counts_blk.at[rows_blk].max(deltas_blk, mode="drop")


def converge_sharded(mesh, counts, local_rows, local_deltas):
    """One anti-entropy merge step over the mesh: every device joins its
    routed slice into its key block. No communication."""
    fn = jax.jit(
        jax.shard_map(
            _local_converge,
            mesh=mesh,
            in_specs=(P("keys", None), P("keys"), P("keys", None)),
            out_specs=P("keys", None),
        ),
        donate_argnums=0,
    )
    return fn(counts, local_rows, local_deltas)


def read_all_sharded(mesh, counts):
    """Row sums (GCOUNT values) for the whole keyspace; output stays
    keys-sharded — only materialise on host what you need."""
    fn = jax.jit(
        jax.shard_map(
            lambda blk: jnp.sum(blk, axis=-1, dtype=UINT64),
            mesh=mesh,
            in_specs=(P("keys", None),),
            out_specs=P("keys"),
        )
    )
    return fn(counts)


def _local_then_pmax(blk):
    # reduce the shard's own replica rows first, then all-reduce across the
    # mesh axis: pmax alone only joins row-for-row across devices
    local = jnp.max(blk, axis=0, keepdims=True)
    joined = jax.lax.pmax(local, "rep")
    return jnp.broadcast_to(joined, blk.shape)


@partial(jax.jit, static_argnames=("mesh",))
def _pmax_join(mesh, counts):
    return jax.shard_map(
        _local_then_pmax,
        mesh=mesh,
        in_specs=(P("rep", "keys"),),
        out_specs=P("rep", "keys"),
    )(counts)


def join_replica_axis(mesh, counts_stacked):
    """Lattice-join full states sharded over the ``rep`` mesh axis.

    counts_stacked: (S, K) or (S, K*R-flattened) sharded P("rep", "keys") —
    S per-replica full states. The join semilattice's all-reduce is a local
    max followed by pmax over ICI (the CRDT analog of gradient psum);
    afterwards every row of every rep-shard holds the converged state.
    """
    return _pmax_join(mesh, counts_stacked)
