"""Key-sharded counter keyspaces: shard_map merge kernels + join collective.

The north-star path (BASELINE.json): PNCOUNT/GCOUNT anti-entropy over a
(keys × replicas) u64 tensor — stored as hi/lo u32 planes (ops/planes.py;
XLA's u64 emulation is 4-25x slower on scatters/reduces) — scaled over a
device mesh:

* **State layout:** each plane sharded ``P("keys", None)`` — a device owns
  a contiguous block of key rows with all replica columns resident, so
  both the join composite and the row-sum read are LOCAL.
* **Routing:** the host assigns key rows blockwise to shards
  (``row // rows_per_shard``); `route_batch` coalesces duplicate keys
  (max-combine — the join composite needs unique rows), buckets per shard,
  and pads to a common width, producing arrays whose leading axis is
  sharded over ``keys``. This is the host-side analog of the reference's
  per-type actor mailbox (repo_manager.pony:92-93) — batching is where the
  reference's per-key loop became one device launch.
* **Merge:** inside `shard_map`, each device runs the same gather ->
  joint-max -> scatter-set composite as the single-chip kernel on its
  block — ZERO collectives on the serving path; the mesh scales
  merges/sec linearly with chips.
* **Join collective:** when full per-replica states arrive sharded over a
  ``rep`` mesh axis (synthetic replicas spread over chips), the lattice
  join across that axis is a local fold + a two-phase u32 pmax (hi plane
  first, then the lo plane masked to hi-winners) — a max-all-reduce over
  ICI, the CRDT analog of data-parallel gradient psum.

All functions are pure and jit/shard_map-composable; dynamic work arrives
pre-padded (static shapes keep XLA's tiling friendly and the jit cache
small).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

# jax.shard_map is the public spelling on newer releases; older
# toolchains (e.g. 0.4.37, the container's pin) still ship it as
# jax.experimental.shard_map.shard_map with the same signature
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - exercised only on older jax pins
    from jax.experimental.shard_map import shard_map

from ..utils.batching import bucket, pad_rows
from ..ops import planes, treg

U32 = jnp.uint32


def shard_plane(mesh, arr):
    """Place one (K, ...) plane keys-sharded on the mesh. K must divide
    evenly by the keys axis (pad capacity with zeros — the lattice
    identity — before calling)."""
    return jax.device_put(arr, NamedSharding(mesh, P("keys", None)))


def shard_vec(mesh, arr):
    """Place one (K,) vector keys-sharded on the mesh."""
    return jax.device_put(arr, NamedSharding(mesh, P("keys")))


def shard_docbatch(mesh, batch):
    """Place a (K, D, W)-planed UJSON DocBatch keys-sharded on the mesh.

    The segmented fold (ops/ujson_device.fold_segments) is embarrassingly
    parallel over its key axis, so with the leading axis sharded the same
    jitted program runs SPMD across the mesh with ZERO collectives —
    UJSON's drain scales with chips exactly like the plane-backed types.
    K must divide evenly by the keys axis (pad with identity groups)."""
    return type(batch)(
        *(
            jax.device_put(
                p, NamedSharding(mesh, P("keys", *([None] * (p.ndim - 1))))
            )
            for p in batch
        )
    )


def _route(key_idx, deltas, n_shards: int, rows_per_shard: int, bucket_width=False):
    """Shared routing core: coalesce, bucket per shard, pad to a common
    width. Returns (local_rows, d_hi, d_lo, slot_rows) where slot_rows maps
    each flattened slot back to its GLOBAL key row (-1 for pad slots).
    With bucket_width the width is padded to a power of two (bounds the jit
    cache over drain sizes)."""
    key_idx, deltas = planes.coalesce(key_idx, deltas)
    shard_of = key_idx // rows_per_shard
    order = np.argsort(shard_of, kind="stable")
    counts = np.bincount(shard_of, minlength=n_shards)
    width = max(int(counts.max()) if len(key_idx) else 0, 1)
    if bucket_width:
        width = bucket(width, 8)
    # distinct out-of-range pads per shard: each device's scatter keeps an
    # honestly-unique index vector
    local_rows = np.broadcast_to(pad_rows(width), (n_shards, width)).copy()
    local_deltas = np.zeros((n_shards, width, deltas.shape[-1]), np.uint64)
    slot_rows = np.full((n_shards, width), -1, np.int64)
    start = 0
    for s in range(n_shards):
        c = int(counts[s])
        sel = order[start : start + c]
        local_rows[s, :c] = key_idx[sel] % rows_per_shard
        local_deltas[s, :c] = deltas[sel]
        slot_rows[s, :c] = key_idx[sel]
        start += c
    return (
        local_rows.reshape(n_shards * width),
        local_deltas.reshape(n_shards * width, deltas.shape[-1]),
        slot_rows.reshape(n_shards * width),
    )


def route_batch(key_idx, deltas, n_shards: int, rows_per_shard: int):
    """Host-side shard routing: global (B,) rows + (B, R) u64 deltas become
    ((n_shards * W,) local rows, hi/lo (n_shards * W, R) u32 planes) with
    the leading axis blockwise-sharded; W is the padded per-shard width.
    Duplicate keys are max-combined here (the device composite requires
    unique rows); padded slots carry PAD_ROW, which the scatter drops.
    """
    local_rows, payload, _ = _route(key_idx, deltas, n_shards, rows_per_shard)
    d_hi, d_lo = planes.split64_np(payload)
    return local_rows, d_hi, d_lo


def route_drain(key_idx, deltas, n_shards: int, rows_per_shard: int):
    """Serving-path routing: like `route_batch`, but the per-shard width is
    bucketed to a power of two (bounds the jit cache over drain sizes) and
    the slot -> global-row map is returned so the host value cache can be
    refreshed from the per-slot sums the sharded drain kernels emit."""
    local_rows, payload, slot_rows = _route(
        key_idx, deltas, n_shards, rows_per_shard, bucket_width=True
    )
    d_hi, d_lo = planes.split64_np(payload)
    return local_rows, d_hi, d_lo, slot_rows


def route_drain64(key_idx, deltas, n_shards: int, rows_per_shard: int):
    """`route_drain` for kernels that take u64 payload columns directly
    (TLOG's segment tensors) instead of hi/lo u32 planes."""
    return _route(key_idx, deltas, n_shards, rows_per_shard, bucket_width=True)


def _local_converge(hi_blk, lo_blk, rows_blk, dhi_blk, dlo_blk):
    """Per-shard join composite (same kernel as ops/gcount.converge_batch,
    applied to this device's key block)."""
    return planes.scatter_join(hi_blk, lo_blk, rows_blk, dhi_blk, dlo_blk)


# jit hoisted to module level with the mesh static: rebuilding the
# jit(shard_map) wrapper per call would retrace and recompile every merge
@partial(jax.jit, static_argnames=("mesh",), donate_argnums=(1, 2))
def _converge_sharded(mesh, hi, lo, local_rows, d_hi, d_lo):
    return shard_map(
        _local_converge,
        mesh=mesh,
        in_specs=(
            P("keys", None),
            P("keys", None),
            P("keys"),
            P("keys", None),
            P("keys", None),
        ),
        out_specs=(P("keys", None), P("keys", None)),
    )(hi, lo, local_rows, d_hi, d_lo)


def converge_sharded(mesh, hi, lo, local_rows, d_hi, d_lo):
    """One anti-entropy merge step over the mesh: every device joins its
    routed slice into its key block. No communication."""
    return _converge_sharded(mesh, hi, lo, local_rows, d_hi, d_lo)


@partial(jax.jit, static_argnames=("mesh",))
def _read_all_sharded(mesh, hi, lo):
    return shard_map(
        planes.rowsum64,
        mesh=mesh,
        in_specs=(P("keys", None), P("keys", None)),
        out_specs=P("keys"),
    )(hi, lo)


def read_all_sharded(mesh, hi, lo):
    """Row sums (counter values, u64 wrapping) for the whole keyspace;
    output stays keys-sharded — only materialise on host what you need."""
    return _read_all_sharded(mesh, hi, lo)


# ---- serving drains: converge + read-back in ONE sharded launch ------------
#
# The counter repos' drain needs the post-join row sums for its host value
# cache. Doing the read inside the same shard_map body keeps the whole
# drain one device launch (no second dispatch latency on the tunneled TPU)
# and keeps read work proportional to the BATCH, not the keyspace: each
# device gathers only its routed rows. Pad slots gather clamped garbage,
# which the host drops via the slot_rows map.


def _local_drain_g(hi_blk, lo_blk, rows_blk, dhi_blk, dlo_blk):
    hi_blk, lo_blk = planes.scatter_join(hi_blk, lo_blk, rows_blk, dhi_blk, dlo_blk)
    sums = planes.rowsum64(hi_blk[rows_blk], lo_blk[rows_blk])
    return hi_blk, lo_blk, sums


@partial(jax.jit, static_argnames=("mesh",), donate_argnums=(1, 2))
def drain_sharded_g(mesh, hi, lo, local_rows, d_hi, d_lo):
    """GCOUNT sharded drain: join the routed batch into each device's key
    block and return (hi, lo, per-slot u64 row sums)."""
    return shard_map(
        _local_drain_g,
        mesh=mesh,
        in_specs=(
            P("keys", None),
            P("keys", None),
            P("keys"),
            P("keys", None),
            P("keys", None),
        ),
        out_specs=(P("keys", None), P("keys", None), P("keys")),
    )(hi, lo, local_rows, d_hi, d_lo)


def _local_drain_pn(p_hi, p_lo, n_hi, n_lo, rows_blk, dhi_blk, dlo_blk):
    # deltas arrive polarity-stacked (W, 2R): one routing pass serves both
    r = p_hi.shape[1]
    p_hi, p_lo = planes.scatter_join(
        p_hi, p_lo, rows_blk, dhi_blk[:, :r], dlo_blk[:, :r]
    )
    n_hi, n_lo = planes.scatter_join(
        n_hi, n_lo, rows_blk, dhi_blk[:, r:], dlo_blk[:, r:]
    )
    p = planes.rowsum64(p_hi[rows_blk], p_lo[rows_blk])
    n = planes.rowsum64(n_hi[rows_blk], n_lo[rows_blk])
    sums = jax.lax.bitcast_convert_type(p - n, jnp.int64)
    return p_hi, p_lo, n_hi, n_lo, sums


@partial(jax.jit, static_argnames=("mesh",), donate_argnums=(1, 2, 3, 4))
def drain_sharded_pn(mesh, p_hi, p_lo, n_hi, n_lo, local_rows, d_hi, d_lo):
    """PNCOUNT sharded drain: both polarities join in one launch; returns
    (state planes..., per-slot i64 net values)."""
    return shard_map(
        _local_drain_pn,
        mesh=mesh,
        in_specs=(
            P("keys", None),
            P("keys", None),
            P("keys", None),
            P("keys", None),
            P("keys"),
            P("keys", None),
            P("keys", None),
        ),
        out_specs=(
            P("keys", None),
            P("keys", None),
            P("keys", None),
            P("keys", None),
            P("keys"),
        ),
    )(p_hi, p_lo, n_hi, n_lo, local_rows, d_hi, d_lo)


# ---- TREG sharded drain ----------------------------------------------------
#
# TREG's keyspace is five (K,) planes (ops/treg.py). Deltas route through
# the same `route_drain` machinery by packing each row's payload as u64
# columns [ts, rank, vid]: rows from the repo's pending dict are UNIQUE,
# so the router's max-coalesce is the identity and the payload columns
# pass through untouched. On device the columns unpack into the plane
# quintuple, the LWW compare-and-scatter runs per key block, and the
# touched rows' (ts, vid) plus the prefix-rank tie flags come back
# per-slot for the host cache / host tie resolution.


def _local_drain_treg(ts_hi, ts_lo, rk_hi, rk_lo, vid, rows_blk, d_hi, d_lo):
    state = treg.TRegState(ts_hi, ts_lo, rk_hi, rk_lo, vid)
    d_vid = d_lo[:, 2].astype(jnp.int32)
    state, tie = treg.converge_batch(
        state, rows_blk, d_hi[:, 0], d_lo[:, 0], d_hi[:, 1], d_lo[:, 1], d_vid
    )
    out_ts_hi = state.ts_hi[rows_blk]
    out_ts_lo = state.ts_lo[rows_blk]
    out_vid = state.vid[rows_blk]
    return (*state, tie, out_ts_hi, out_ts_lo, out_vid)


@partial(jax.jit, static_argnames=("mesh",), donate_argnums=(1, 2, 3, 4, 5))
def drain_sharded_treg(mesh, ts_hi, ts_lo, rk_hi, rk_lo, vid, local_rows, d_hi, d_lo):
    """TREG sharded drain: LWW-join the routed batch into each device's
    key block; returns (5 state planes, per-slot tie flags, per-slot
    ts_hi/ts_lo/vid read-back)."""
    return shard_map(
        _local_drain_treg,
        mesh=mesh,
        in_specs=(
            P("keys"),
            P("keys"),
            P("keys"),
            P("keys"),
            P("keys"),
            P("keys"),
            P("keys", None),
            P("keys", None),
        ),
        out_specs=(
            P("keys"),
            P("keys"),
            P("keys"),
            P("keys"),
            P("keys"),
            P("keys"),
            P("keys"),
            P("keys"),
            P("keys"),
        ),
    )(ts_hi, ts_lo, rk_hi, rk_lo, vid, local_rows, d_hi, d_lo)


def _local_patch_treg(vid, rows_blk, patch_vid):
    return vid.at[rows_blk].set(patch_vid, mode="drop", unique_indices=True)


@partial(jax.jit, static_argnames=("mesh",), donate_argnums=(1,))
def patch_sharded_treg(mesh, vid, local_rows, patch_vid):
    """Host-resolved prefix-rank ties scatter their winning vids back."""
    return shard_map(
        _local_patch_treg,
        mesh=mesh,
        in_specs=(P("keys"), P("keys"), P("keys")),
        out_specs=P("keys"),
    )(vid, local_rows, patch_vid)


# ---- TLOG sharded drain ----------------------------------------------------
#
# TLOG's keyspace is (K, L) ts/vid segment tensors + (K,) length/cutoff
# vectors (ops/tlog.py). Deltas route as u64 payload columns
# [ts(ld) | vid(ld) | cutoff | count], unpacked per device block; the
# batched sort-dedup-mask merge runs shard-local, then the fused trim
# applies where count < TRIM_NOOP — so drains, trims, and drain+trim are
# all ONE dispatch. NOT donated: the caller retries from the pre-merge
# state when a row overflows its slot budget.


def _local_drain_tlog(nth, ntl, nv, length, cutoff, rows_blk, payload, ld):
    from ..ops import tlog as tlog_ops

    state = tlog_ops.TLogState(nth, ntl, nv, length, cutoff)
    d_ts = payload[:, :ld]
    d_vid = payload[:, ld : 2 * ld].astype(jnp.int64)
    d_cut = payload[:, 2 * ld]
    counts = payload[:, 2 * ld + 1].astype(jnp.int64)
    st, ovf = tlog_ops.converge_then_trim(
        state, rows_blk, d_ts, d_vid, d_cut, rows_blk, counts
    )
    return (*st, ovf, st.length[rows_blk], st.cutoff[rows_blk])


@partial(jax.jit, static_argnames=("mesh", "ld"))
def drain_sharded_tlog(mesh, nth, ntl, nv, length, cutoff, local_rows, payload, ld):
    """TLOG sharded drain (+ fused optional per-row trim) over the wide
    3-plane layout; returns (5 state tensors, per-slot overflow flags,
    per-slot lengths, per-slot cutoffs)."""
    return shard_map(
        partial(_local_drain_tlog, ld=ld),
        mesh=mesh,
        in_specs=(
            P("keys", None),
            P("keys", None),
            P("keys", None),
            P("keys"),
            P("keys"),
            P("keys"),
            P("keys", None),
        ),
        out_specs=(
            P("keys", None),
            P("keys", None),
            P("keys", None),
            P("keys"),
            P("keys"),
            P("keys"),
            P("keys"),
            P("keys"),
        ),
    )(nth, ntl, nv, length, cutoff, local_rows, payload)


def _tree_join(hi_blk, lo_blk):
    """Log-depth joint fold over the leading axis."""
    while hi_blk.shape[0] > 1:
        s = hi_blk.shape[0]
        half = s // 2
        fhi, flo = planes.join_max(
            hi_blk[:half], lo_blk[:half], hi_blk[half : 2 * half], lo_blk[half : 2 * half]
        )
        if s % 2:  # odd leftover rides along
            fhi = jnp.concatenate([fhi, hi_blk[-1:]])
            flo = jnp.concatenate([flo, lo_blk[-1:]])
        hi_blk, lo_blk = fhi, flo
    return hi_blk, lo_blk


def _local_then_pmax(hi_blk, lo_blk):
    # fold the shard's own replica rows jointly first (pmax alone only
    # joins row-for-row across devices), then two-phase u32 all-reduce:
    # hi decides; lo competes only where hi is the winner
    fhi, flo = _tree_join(hi_blk, lo_blk)
    jhi = jax.lax.pmax(fhi, "rep")
    lo_cand = jnp.where(fhi == jhi, flo, jnp.uint32(0))
    jlo = jax.lax.pmax(lo_cand, "rep")
    return (
        jnp.broadcast_to(jhi, hi_blk.shape),
        jnp.broadcast_to(jlo, lo_blk.shape),
    )


@partial(jax.jit, static_argnames=("mesh",))
def _pmax_join(mesh, hi, lo):
    return shard_map(
        _local_then_pmax,
        mesh=mesh,
        in_specs=(P("rep", "keys"), P("rep", "keys")),
        out_specs=(P("rep", "keys"), P("rep", "keys")),
    )(hi, lo)


def join_replica_axis(mesh, hi_stacked, lo_stacked):
    """Lattice-join full states sharded over the ``rep`` mesh axis.

    hi/lo_stacked: (S, K) u32 planes sharded P("rep", "keys") — S
    per-replica full u64 states. Afterwards every row of every rep-shard
    holds the converged state.
    """
    return _pmax_join(mesh, hi_stacked, lo_stacked)
