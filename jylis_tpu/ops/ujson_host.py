"""UJSON: nested observed-remove maps/sets with causal add-wins semantics.

Host-side authoritative implementation of the documented lattice
(docs/_docs/types/ujson.md:134-182): a UJSON node is a flat set of
(path, primitive-value) pairs, each tagged with a causal dot
(replica-id, seq); removal is by causal context (observed-remove), and a
concurrent insert of an identical pair beats its removal (add-wins).
Reference repo driving it: jylis/repo_ujson.pony:28-110.

The dot-store is an ORSWOT-style delta CRDT (Almeida et al.,
"Efficient State-based CRDTs by Delta-Mutation", PAPERS.md): a mutation's
delta carries only the new entries plus a causal context covering the new
dots and every removed dot. Joins are: keep an entry iff it is present in
both sides, or present in one side and its dot is NOT covered by the other
side's context (i.e. the other side never observed it — it survives).

This lattice lives on the host for SERVING: per-document data is tiny and
pointer-heavy. The anti-entropy fan-in — joining many deltas into many
replicas — is tensorised in ops/ujson_device.py (sorted packed-dot rows,
vv planes, log-depth delta folds), differentially tested against this
oracle and measured faster than the host loop on the 32-replica
benchmark (bench.py --config ujson-32).

Values are stored as canonical JSON tokens (the exact primitive serialisation,
e.g. '"user"', '42', 'true', 'null') so value identity is representation
identity — 1 and 1.0 stay distinct, matching string-typed storage in the
reference.
"""

from __future__ import annotations

import json

Dot = tuple[int, int]  # (replica-id, seq)
Path = tuple[str, ...]


class CausalContext:
    """Compacted causal history: per-replica contiguous max (version vector)
    plus a cloud of out-of-band dots (ujson.md:176 — compaction keeps this
    bounded)."""

    __slots__ = ("vv", "cloud")

    def __init__(self):
        self.vv: dict[int, int] = {}
        self.cloud: set[Dot] = set()

    def contains(self, dot: Dot) -> bool:
        r, s = dot
        return s <= self.vv.get(r, 0) or dot in self.cloud

    def __eq__(self, other) -> bool:
        """REPRESENTATIONAL equality (vv and cloud as stored) — what the
        wire codec round-trips; two contexts with identical coverage but
        different compaction states compare unequal."""
        return (
            isinstance(other, CausalContext)
            and self.vv == other.vv
            and self.cloud == other.cloud
        )

    # defining __eq__ sets __hash__ to None implicitly; keep that intent
    # EXPLICIT: contexts are mutable lattice state and must never be
    # dict keys or set members (a silent identity-hash would let two
    # equal contexts land in different buckets)
    __hash__ = None

    def add(self, dot: Dot) -> None:
        self.cloud.add(dot)
        self.compact()

    def next_dot(self, replica: int) -> Dot:
        """Mint the next contiguous dot for a replica (local mutations only)."""
        s = self.vv.get(replica, 0) + 1
        self.vv[replica] = s
        return (replica, s)

    def join(self, other: "CausalContext") -> None:
        for r, s in other.vv.items():
            if s > self.vv.get(r, 0):
                self.vv[r] = s
        self.cloud |= other.cloud
        self.compact()

    def compact(self) -> None:
        moved = True
        while moved:
            moved = False
            for dot in list(self.cloud):
                r, s = dot
                top = self.vv.get(r, 0)
                if s == top + 1:
                    self.vv[r] = s
                    self.cloud.discard(dot)
                    moved = True
                elif s <= top:
                    self.cloud.discard(dot)
                    moved = True


def parse_doc(doc: str) -> list[tuple[Path, str]]:
    """Parse a JSON document into its UJSON leaves: (relative-path, token).

    Maps extend the path; sets (JSON arrays) do NOT contribute path
    components, which is exactly why nested sets flatten and sibling maps
    in a set merge (ujson.md:165-170).
    """
    data = json.loads(doc)
    leaves: list[tuple[Path, str]] = []

    def walk(node, path: Path):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, path + (str(k),))
        elif isinstance(node, list):
            for v in node:
                walk(v, path)
        else:
            leaves.append((path, json.dumps(node)))

    walk(data, ())
    return leaves


def parse_value(doc: str) -> str:
    """Parse a single JSON primitive (INS/RM argument) to its token; raises
    ValueError on maps/sets (ujson.md:83)."""
    data = json.loads(doc)
    if isinstance(data, (dict, list)):
        raise ValueError("value must be a JSON primitive")
    return json.dumps(data)


class UJSON:
    """One document: dot-store + causal context, with delta-mutators.

    Every mutator takes an optional ``delta`` UJSON accumulating the minimal
    joinable state of the mutation (the reference's delta-accumulator
    pattern, repo_ujson.pony:53-66); deltas for the same document within a
    flush window coalesce by join.
    """

    __slots__ = ("entries", "ctx", "_by_path", "_idx_of")

    def __init__(self):
        self.entries: dict[Dot, tuple[Path, str]] = {}
        self.ctx = CausalContext()
        self._by_path: dict[Path, set[Dot]] | None = None
        self._idx_of: dict | None = None

    # -- per-path index over the dot-store ----------------------------------
    #
    # set_doc/rm/clr observe (then remove) the dots at or under a path;
    # scanning every entry per write made write-hot documents quadratic —
    # the measured floor of the all-commands serving mix (bench.py
    # `concurrent`, where 95% of mix time was this scan). The index maps
    # path -> dots, built lazily at the first observe and maintained by
    # the internal mutators; it is keyed on the entries dict's IDENTITY,
    # so consumers that install a fresh entries dict wholesale
    # (LazyWireUJSON._materialize, test fixtures) invalidate it by
    # construction. Code outside this class must never mutate an
    # existing entries dict in place after the doc has served a write —
    # decode paths populate entries only at construction, before any
    # index exists.

    def _index(self) -> dict[Path, set[Dot]]:
        if getattr(self, "_idx_of", None) is not self.entries:
            idx: dict[Path, set[Dot]] = {}
            for d, (p, _) in self.entries.items():
                s = idx.get(p)
                if s is None:
                    s = idx[p] = set()
                s.add(d)
            self._by_path = idx
            self._idx_of = self.entries
        return self._by_path

    def _idx_add(self, dot: Dot, path: Path) -> None:
        if getattr(self, "_idx_of", None) is self.entries:
            s = self._by_path.get(path)
            if s is None:
                s = self._by_path[path] = set()
            s.add(dot)

    def _idx_drop(self, dot: Dot, path: Path) -> None:
        if getattr(self, "_idx_of", None) is self.entries:
            s = self._by_path.get(path)
            if s is not None:
                s.discard(dot)
                if not s:
                    del self._by_path[path]

    def __eq__(self, other) -> bool:
        """Representational equality (see CausalContext.__eq__): used by
        message equality in the codec differential tests."""
        return (
            isinstance(other, UJSON)
            and self.entries == other.entries
            and self.ctx == other.ctx
        )

    __hash__ = None  # see CausalContext.__hash__: mutable, never hashable

    # ---- queries ----------------------------------------------------------

    def _under(self, path: Path) -> list[Dot]:
        n = len(path)
        out: list[Dot] = []
        for p, dots in self._index().items():
            if p[:n] == path:
                out.extend(dots)
        return out

    def is_empty(self) -> bool:
        return not self.entries

    def render(self, path: Path = ()) -> str:
        """Render the subtree at path as compact JSON; "" when absent
        (ujson.md:34-38). Set/map member order is unspecified by the
        semantics; we emit a deterministic sorted order."""
        n = len(path)
        values: set[str] = set()
        children: dict[str, bool] = {}
        for p, token in self.entries.values():
            if p[:n] != path:
                continue
            if len(p) == n:
                values.add(token)
            else:
                children[p[n]] = True
        if not values and not children:
            return ""
        rendered_map = None
        if children:
            items = sorted(children)
            rendered_map = (
                "{" + ",".join(json.dumps(k) + ":" + self.render(path + (k,)) for k in items) + "}"
            )
        vals = sorted(values)
        if rendered_map is None:
            return vals[0] if len(vals) == 1 else "[" + ",".join(vals) + "]"
        if not vals:
            return rendered_map
        return "[" + ",".join(vals + [rendered_map]) + "]"

    # ---- mutators ---------------------------------------------------------

    def _remove_dots(self, dots, delta: "UJSON | None") -> None:
        """Observed-remove: drop entries and record their dots in our context
        and in the delta's context (no delta entries -> receiver removes).
        A dot the SAME delta window added must also drop out of the
        delta's entries: an entry whose dot its own context covers reads
        as LIVE to any converger, so leaving it would resurrect the
        removed value on every receiver that had not yet seen the add
        (same-window SET+RM over anti-entropy, journal replay)."""
        for d in dots:
            pv = self.entries.pop(d, None)
            if pv is not None:
                self._idx_drop(d, pv[0])
            self.ctx.add(d)
            if delta is not None:
                dpv = delta.entries.pop(d, None)
                if dpv is not None:
                    delta._idx_drop(d, dpv[0])
                delta.ctx.add(d)

    def _add_leaf(self, replica: int, path: Path, token: str, delta) -> None:
        dot = self.ctx.next_dot(replica)
        self.entries[dot] = (path, token)
        self._idx_add(dot, path)
        if delta is not None:
            delta.entries[dot] = (path, token)
            delta._idx_add(dot, path)
            delta.ctx.add(dot)

    def set_doc(self, replica: int, path: Path, doc: str, delta=None) -> None:
        """SET: clear the subtree (observed dots only), then add the parsed
        leaves under fresh dots (ujson.md:44-61)."""
        leaves = parse_doc(doc)
        self._remove_dots(self._under(path), delta)
        for sub, token in leaves:
            self._add_leaf(replica, path + sub, token, delta)

    def ins(self, replica: int, path: Path, value: str, delta=None) -> None:
        """INS: add one primitive alongside existing values (ujson.md:77-89)."""
        self._add_leaf(replica, path, parse_value(value), delta)

    def rm(self, replica: int, path: Path, value: str, delta=None) -> None:
        """RM: remove the observed dots of one exact (path, value) pair
        (ujson.md:91-103)."""
        token = parse_value(value)
        dots = [
            d
            for d in self._index().get(path, ())
            if self.entries[d][1] == token
        ]
        self._remove_dots(dots, delta)

    def clr(self, replica: int, path: Path, delta=None) -> None:
        """CLR: remove all observed dots at or under path (ujson.md:63-75)."""
        self._remove_dots(self._under(path), delta)

    # ---- lattice ----------------------------------------------------------

    def converge(self, other: "UJSON") -> bool:
        """ORSWOT join; returns True if local state changed."""
        changed = False
        # entries present only here, observed (covered) by other -> removed
        for d in list(self.entries):
            if d not in other.entries and other.ctx.contains(d):
                pv = self.entries.pop(d)
                self._idx_drop(d, pv[0])
                changed = True
        # entries present only there, not covered by us -> added
        for d, pv in other.entries.items():
            if d not in self.entries and not self.ctx.contains(d):
                self.entries[d] = pv
                self._idx_add(d, pv[0])
                changed = True
        before = (dict(self.ctx.vv), set(self.ctx.cloud))
        self.ctx.join(other.ctx)
        if (self.ctx.vv, self.ctx.cloud) != before:
            changed = True
        return changed
