"""UJSON deltas as WIRE BYTES: the zero-Python-objects anti-entropy path.

The round-3 receive pipeline decoded every inbound UJSON delta into a
host ``UJSON`` object (dict-of-dots + context), only for the resident
drain to immediately re-flatten those dicts into packed device planes —
two Python walks per delta on the hot path. This module removes both:

* ``WireUJSON`` — a lazy ``UJSON`` subclass holding the delta's raw wire
  payload (the oracle shape, cluster/codec.py ``delta/UJSON``) plus the
  counts/max-seq the native splitter measured. It materialises the dict
  form only when something actually touches ``.entries``/``.ctx`` (the
  host-lattice fallback paths); device-bound deltas never do.
* ``split_push_ujson(body)`` — one native pass over a PushDeltas body
  (native/ujson_planes.cpp) returning per-key payload spans + counts,
  with structure AND utf-8 validated up front so later materialisation
  cannot fail mid-serving.
* ``grid_from_wire(...)`` — the resident drain's grid encoder: native
  measure+fill straight from concatenated wire payloads into the padded
  (rows, W) dot/pay/vv/cloud planes `ops/ujson_resident` folds, with
  replica-ids interned against the store's global columns inside the
  call and payloads interned by their canonical wire bytes (identical
  (path, token) pairs have identical encodings). Per-delta host cost is
  a few native ops instead of a Python dict walk.

``read_ujson`` is the single Python implementation of the wire shape
(the codec oracle calls it too); parity between it, the native splitter,
and the native grid encoder is fuzz-checked in tests/test_ujson_wire.py.
"""

from __future__ import annotations

import ctypes

import numpy as np

from ..utils.wire import Reader, WireError
from .ujson_device import PAD32, PAD64
from .ujson_host import UJSON, CausalContext


def read_ujson(r: Reader) -> UJSON:
    """Parse one UJSON delta payload at the reader's position (the
    oracle wire shape: entries, vv, cloud)."""
    u = UJSON()
    for _ in range(r.varint()):
        rid, seq = r.varint(), r.varint()
        path = tuple(r.str_() for _ in range(r.varint()))
        u.entries[(rid, seq)] = (path, r.str_())
    u.ctx.vv = {r.varint(): r.varint() for _ in range(r.varint())}
    u.ctx.cloud = {(r.varint(), r.varint()) for _ in range(r.varint())}
    return u


class WireUJSON(UJSON):
    """A UJSON delta carried as its wire payload, materialised lazily.

    Everything that treats it as a document (host converge, render,
    equality) works through the ``entries``/``ctx`` properties; the
    resident drain recognises the type and consumes ``raw`` directly.
    Deltas are immutable in every consumer, so the measured counts stay
    exact whether or not materialisation ever happens.
    """

    __slots__ = ("raw", "n_entries", "n_vv", "n_cloud", "max_seq", "_mat")

    def __init__(
        self, raw: bytes, n_entries: int, n_vv: int, n_cloud: int, max_seq: int
    ):
        # deliberately NO placeholder entries/ctx: deltas are created in
        # bulk on the receive hot path, and the dict/context objects
        # would be 4 dead allocations per delta for the device-bound case
        self.raw = raw
        self.n_entries = n_entries
        self.n_vv = n_vv
        self.n_cloud = n_cloud
        self.max_seq = max_seq
        self._mat = False

    def _materialize(self) -> None:
        if self._mat:
            return
        r = Reader(self.raw)
        u = read_ujson(r)
        if not r.done():
            raise WireError("trailing bytes in UJSON payload")
        UJSON.entries.__set__(self, u.entries)
        UJSON.ctx.__set__(self, u.ctx)
        self._mat = True

    @property
    def entries(self):
        self._materialize()
        return UJSON.entries.__get__(self)

    @property
    def ctx(self):
        self._materialize()
        return UJSON.ctx.__get__(self)


# ---- native wrappers -------------------------------------------------------


def split_push_ujson(body: bytes) -> list[tuple[bytes, WireUJSON]] | None:
    """Split a PushDeltas body (past tag+name) into per-key WireUJSON
    deltas in ONE native pass — no per-entry Python work. Returns None
    when the native library is absent or the bytes are outside the fast
    path's domain (malformed, varints past u64): the caller falls back
    to the oracle, which decodes or raises properly."""
    from ..native import lib
    from ..native.codec import _ptr

    cdll = lib()
    if cdll is None:
        return None
    n_keys = ctypes.c_int64()
    rc = cdll.jy_ujson_split_measure(body, len(body), ctypes.byref(n_keys))
    if rc != 0:
        return None
    nk = n_keys.value
    key_off = np.empty(nk, np.int64)
    key_len = np.empty(nk, np.int64)
    pay_off = np.empty(nk, np.int64)
    pay_len = np.empty(nk, np.int64)
    n_entries = np.empty(nk, np.int64)
    n_vv = np.empty(nk, np.int64)
    n_cloud = np.empty(nk, np.int64)
    max_seq = np.empty(nk, np.uint64)
    rc = cdll.jy_ujson_split(
        body, len(body), _ptr(key_off), _ptr(key_len), _ptr(pay_off),
        _ptr(pay_len), _ptr(n_entries), _ptr(n_vv), _ptr(n_cloud),
        _ptr(max_seq),
    )
    if rc != 0:
        return None
    ko, kl = key_off.tolist(), key_len.tolist()
    po, pl = pay_off.tolist(), pay_len.tolist()
    ne, nv, nc = n_entries.tolist(), n_vv.tolist(), n_cloud.tolist()
    ms = max_seq.tolist()
    return [
        (
            body[ko[k] : ko[k] + kl[k]],
            WireUJSON(body[po[k] : po[k] + pl[k]], ne[k], nv[k], nc[k], ms[k]),
        )
        for k in range(nk)
    ]


class GridOverflow(Exception):
    """The wire grid needs a layout the caller's shift can't hold."""


class GridRepBudget(Exception):
    """Replica columns exceeded the vv plane; grow n_rep and retry."""

    def __init__(self, needed: int):
        self.needed = needed


def grid_from_wire(
    deltas: list[WireUJSON],
    dest_rows: np.ndarray,
    rows: int,
    w: int,
    c: int,
    shift: int,
    n_rep: int,
    known_rids: list[int],
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, list[int], list[bytes]]:
    """Native wire->planes encode: fill padded (rows, w/c/n_rep) planes
    from raw delta payloads. Replica ids intern against known_rids
    (store columns, in column order); NEW rids get the next columns and
    are returned for the caller to merge. Payload ids in the returned
    pay plane are CALL-LOCAL; the caller remaps them through the
    returned canonical wire spans (see ResidentStore._encode_grid_wire).

    Raises GridOverflow (needs a wider shift) / GridRepBudget (needs a
    wider vv plane); both leave no visible state."""
    from ..native import lib
    from ..native.codec import _ptr

    cdll = lib()
    n = len(deltas)
    d_off = np.empty(n, np.int64)
    d_len = np.empty(n, np.int64)
    pos = 0
    parts = []
    for i, d in enumerate(deltas):
        raw = d.raw
        d_off[i] = pos
        d_len[i] = len(raw)
        pos += len(raw)
        parts.append(raw)
    blob = b"".join(parts)
    dtype = np.int32 if shift < 32 else np.uint64
    pad = PAD32 if shift < 32 else PAD64
    dots = np.full((rows, w), pad, dtype)
    pay = np.full((rows, w), -1, np.int32)
    vv = np.zeros((rows, n_rep), np.uint32)
    cloud = np.full((rows, c), pad, dtype)
    known = np.asarray(known_rids, np.uint64)
    total_ent = int(sum(d.n_entries for d in deltas))
    # every rid occurrence can be distinct: entries + vv + cloud all intern
    rid_cap = (
        len(known) + total_ent
        + int(sum(d.n_vv + d.n_cloud for d in deltas)) + 64
    )
    new_rids = np.empty(rid_cap, np.uint64)
    pay_span_off = np.empty(max(total_ent, 1), np.int64)
    pay_span_len = np.empty(max(total_ent, 1), np.int64)
    n_new = ctypes.c_int64()
    n_pays = ctypes.c_int64()
    rids_seen = ctypes.c_int64()
    rc = cdll.jy_ujson_grid_fill(
        blob, n, _ptr(d_off), _ptr(d_len), _ptr(dest_rows),
        ctypes.c_int32(shift), w, c, n_rep,
        _ptr(known), len(known),
        _ptr(dots), _ptr(pay), _ptr(vv), _ptr(cloud),
        _ptr(new_rids), ctypes.byref(n_new),
        _ptr(pay_span_off), _ptr(pay_span_len), ctypes.byref(n_pays),
        ctypes.byref(rids_seen),
    )
    if rc == -2:
        raise GridOverflow()
    if rc == -3:
        raise GridRepBudget(rids_seen.value)
    if rc != 0:
        raise WireError("malformed UJSON wire payload in grid encode")
    spans = [
        blob[int(pay_span_off[i]) : int(pay_span_off[i]) + int(pay_span_len[i])]
        for i in range(n_pays.value)
    ]
    return dots, pay, vv, cloud, new_rids[: n_new.value].tolist(), spans
