"""TENSOR: batched per-coordinate tensor joins as device kernels.

The device mirror of ops/tensor_host.py — the first lattice in this
repo whose VALUES are tensors, so the (keys x dims) planes are finally
the shape the north-star device path exists for: thousands of vector
merges collapse into one XLA launch (ROADMAP item 3; arXiv:2605.19373,
arXiv:2607.01308).

Layout: the keyspace is four (N, D) planes —

    val    u32  raw f32 bit patterns (okey-comparable, see below)
    ts_hi  u32  } u64 per-coordinate timestamp as hi/lo planes
    ts_lo  u32  } (the planes.py u64-emulation posture: u32 ops only)
    rid    u32  writer replica-id tiebreak

One row is one MAX/LWW register vector, or one AVG CONTRIBUTION (the
repo maps AVG keys to one device row per contributing replica, so all
three merge modes run the SAME kernel). The join is a per-coordinate
lexicographic select on ``(ts, rid, okey(val))``:

* LWW rows carry real (ts, rid) stamps — the select IS per-coordinate
  last-writer-wins with replica-id tiebreak and a value-bits total
  order at the bottom.
* MAX rows carry ts = rid = 0 — the select degenerates to elementwise
  float max via ``okey``, the order-preserving u32 transform of the f32
  bit pattern (sign-flip trick: unsigned integer compares match IEEE
  order, totalised; the canonical quiet NaN is the per-coordinate top,
  bit pattern 0xFFFFFFFF — okey 0 — is the identity padding).
* AVG contribution rows carry a LOCAL monotone version stamp in the ts
  planes (rid broadcast per row) — the host joins same-rid
  contributions as whole vectors (lexicographic (ts, okey-tuple)),
  which no per-coordinate select can reproduce at equal-ts ties, so
  the mirror takes the host's latest whole-vector winner instead
  (models/repo_tensor.py drain).

``join_dense`` is literally ``jax.vmap`` of the one-row join over the
keys axis — the "one vmap'd XLA join over (keys x dims) planes" the
type was specified as. NaN canonicalisation happens at the host
boundary (tensor_host.canon_f32); these kernels only ever see
canonical bit patterns and compare them as integers, so no float
comparison semantics leak into the lattice.

Contract: one batch holds at most one delta per row (the repos
coalesce per key host-side, the repo_gcount.pony:43-48 pattern);
``converge_many`` folds several replica batches in one compiled scan.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

U32 = jnp.uint32

# per-coordinate identity bits: okey == 0, below every canonical float
BOTTOM_BITS = 0xFFFFFFFF


class TensorState(NamedTuple):
    val: jax.Array  # (N, D) uint32 f32 bit patterns
    ts_hi: jax.Array  # (N, D) uint32
    ts_lo: jax.Array  # (N, D) uint32
    rid: jax.Array  # (N, D) uint32


def init(num_rows: int, dim: int) -> TensorState:
    return TensorState(
        jnp.full((num_rows, dim), BOTTOM_BITS, U32),
        jnp.zeros((num_rows, dim), U32),
        jnp.zeros((num_rows, dim), U32),
        jnp.zeros((num_rows, dim), U32),
    )


def _okey(u):
    """Order-preserving u32 transform of f32 bits (tensor_host.okey_u32)."""
    return jnp.where(
        (u >> jnp.uint32(31)).astype(jnp.bool_), ~u, u | jnp.uint32(0x80000000)
    )


def _b_wins(a: tuple, b: tuple):
    """Per-coordinate strict (ts, rid, okey(val)) dominance of B over A.

    A total order on cells: ties on all four u32 components mean the
    cells are bit-identical, so strict-greater select is commutative,
    associative, and idempotent by construction."""
    a_v, a_th, a_tl, a_r = a
    b_v, b_th, b_tl, b_r = b
    ts_gt = (b_th > a_th) | ((b_th == a_th) & (b_tl > a_tl))
    ts_eq = (b_th == a_th) & (b_tl == a_tl)
    return ts_gt | (
        ts_eq & ((b_r > a_r) | ((b_r == a_r) & (_okey(b_v) > _okey(a_v))))
    )


def _join_row(a_v, a_th, a_tl, a_r, b_v, b_th, b_tl, b_r):
    """Join ONE row's (D,) cell vectors — the unit the keys axis vmaps."""
    wins = _b_wins((a_v, a_th, a_tl, a_r), (b_v, b_th, b_tl, b_r))
    return (
        jnp.where(wins, b_v, a_v),
        jnp.where(wins, b_th, a_th),
        jnp.where(wins, b_tl, a_tl),
        jnp.where(wins, b_r, a_r),
    )


# the dense full-keyspace join: one row-join vmapped over the keys axis
_join_rows = jax.vmap(_join_row)


def join_dense(state: TensorState, deltas: TensorState) -> TensorState:
    """Full-keyspace elementwise join — each plane streamed exactly once
    (the north-star dense shape; rows with no delta carry the identity
    (BOTTOM_BITS, 0, 0, 0), which never wins)."""
    return TensorState(*_join_rows(*state, *deltas))


def converge_batch(
    state: TensorState,
    key_idx: jax.Array,
    d_val: jax.Array,
    d_ts_hi: jax.Array,
    d_ts_lo: jax.Array,
    d_rid: jax.Array,
) -> TensorState:
    """Join one delta batch at UNIQUE rows: gather the current (B, D)
    cell blocks, vmap the row join over the batch, scatter both back
    (mode="drop" for pad rows)."""
    cur = tuple(plane[key_idx] for plane in state)
    new = _join_rows(*cur, d_val, d_ts_hi, d_ts_lo, d_rid)
    return TensorState(
        *(
            plane.at[key_idx].set(nv, mode="drop", unique_indices=True)
            for plane, nv in zip(state, new)
        )
    )


def converge_many(
    state: TensorState,
    key_idx: jax.Array,
    d_val: jax.Array,
    d_ts_hi: jax.Array,
    d_ts_lo: jax.Array,
    d_rid: jax.Array,
) -> TensorState:
    """Fold several replica batches ((R, B)-indexed inputs) in one
    compiled scan — a whole multi-replica anti-entropy round as a
    single dispatch, for offline folds where batches arrive pre-formed
    (the treg.converge_many posture; NOT on the serving path, which
    coalesces per key host-side and drains one batch)."""

    def step(st, batch):
        ki, v, th, tl, r = batch
        return converge_batch(st, ki, v, th, tl, r), None

    out, _ = jax.lax.scan(
        step, state, (key_idx, d_val, d_ts_hi, d_ts_lo, d_rid)
    )
    return out


def read(state: TensorState, key_idx: jax.Array) -> jax.Array:
    """Gather raw f32 bit-pattern rows for a batch of row indices."""
    return state.val[key_idx]


def grow(state: TensorState, num_rows: int, dim: int) -> TensorState:
    n, d = state.val.shape
    if (num_rows, dim) == (n, d):
        return state
    fresh = init(num_rows, dim)
    return TensorState(
        *(
            f.at[:n, :d].set(p)
            for f, p in zip(fresh, state)
        )
    )
