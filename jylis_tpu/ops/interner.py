"""String interning: host strings <-> device integer ids.

Keys and values live on the host; the device sees only integer ids plus an
order-preserving 8-byte prefix rank so lattice tie-breaks that the reference
resolves "by sorting rules" (bytewise string comparison,
docs/_docs/types/treg.md:60-63, tlog.md:124-127) can run on-device. Two
strings with the same 8-byte prefix but different tails compare equal on
device; callers get a tie flag and resolve those rare cases on host with the
full strings.
"""

from __future__ import annotations

import numpy as np

from ..utils.batching import bucket

_PAD = b"\x00" * 8


def prefix_rank(s: bytes) -> int:
    """Order-preserving uint64: big-endian first 8 bytes, zero padded.

    rank(a) < rank(b) implies a < b bytewise; equality is inconclusive
    (prefix collision) unless both strings are <= 8 bytes.
    """
    return int.from_bytes((s[:8] + _PAD)[:8], "big")


class Interner:
    """Bidirectional bytes<->id table with a device-shippable rank array.

    Ids are dense and never reused BETWEEN compactions; id equality is
    exact string equality, which is what the device dedup kernels rely on
    (e.g. TLOG duplicate detection requires equal timestamp AND equal
    value, docs/_docs/types/tlog.md:122). Long-running write churn
    (TREG overwrites, TLOG trims) strands dead ids; owners periodically
    `compact` with their live-id set and remap every stored id — host
    caches and device planes alike — so memory tracks the LIVE state,
    not the write history."""

    __slots__ = ("_to_id", "_strings", "_ranks", "_cap")

    def __init__(self, initial_capacity: int = 1024):
        self._to_id: dict[bytes, int] = {}
        self._strings: list[bytes] = []
        self._cap = max(int(initial_capacity), 16)
        self._ranks = np.zeros(self._cap, dtype=np.uint64)

    def __len__(self) -> int:
        return len(self._strings)

    # ids must stay below 2**31 - 1: the TLOG sort planes carry the biased
    # id (vid + 1) in one u32 lane (ops/tlog._planes). Unreachable in
    # practice — two billion live strings would exhaust host memory first,
    # and compaction keeps ids dense — but fail loudly, never corrupt.
    MAX_ID = (1 << 31) - 2

    def intern(self, s: bytes) -> int:
        sid = self._to_id.get(s)
        if sid is None:
            sid = len(self._strings)
            if sid > self.MAX_ID:
                raise RuntimeError(
                    "interner id space exhausted (2**31 ids); compaction "
                    "should have reclaimed dead ids long before this"
                )
            self._to_id[s] = sid
            self._strings.append(s)
            if sid >= self._cap:
                self._cap *= 2
                ranks = np.zeros(self._cap, dtype=np.uint64)
                ranks[: len(self._ranks)] = self._ranks
                self._ranks = ranks
            self._ranks[sid] = prefix_rank(s)
        return sid

    def intern_many(self, strings) -> np.ndarray:
        return np.fromiter(
            (self.intern(s) for s in strings), dtype=np.int64, count=len(strings)
        )

    def lookup(self, sid: int) -> bytes:
        return self._strings[sid]

    def rank(self, sid: int) -> int:
        return int(self._ranks[sid])

    def ranks_for(self, ids: np.ndarray) -> np.ndarray:
        """Vectorised id -> rank (ids must be valid; -1 maps to rank 0)."""
        ids = np.asarray(ids, dtype=np.int64)
        out = np.zeros(ids.shape, dtype=np.uint64)
        valid = ids >= 0
        out[valid] = self._ranks[ids[valid]]
        return out

    def contains(self, s: bytes) -> bool:
        return s in self._to_id

    def compact(self, live_ids) -> np.ndarray:
        """Drop every string not in `live_ids` (ints, repeats fine).

        Returns the remap array: old id -> new id, -1 for dead ids. The
        caller MUST apply it to every place an old id is stored (host
        caches, device planes) before interning anything new — old and
        new ids share the same space."""
        remap = np.full(len(self._strings), -1, np.int64)
        new_strings: list[bytes] = []
        for oid in live_ids:
            oid = int(oid)
            if remap[oid] < 0:
                remap[oid] = len(new_strings)
                new_strings.append(self._strings[oid])
        self._strings = new_strings
        self._to_id = {s: i for i, s in enumerate(new_strings)}
        self._cap = bucket(len(new_strings), 16)
        ranks = np.zeros(self._cap, dtype=np.uint64)
        for i, s in enumerate(new_strings):
            ranks[i] = prefix_rank(s)
        self._ranks = ranks
        return remap
