"""TREG: last-writer-wins timestamped register as batched TPU kernels.

Semantics (docs/_docs/types/treg.md:56-63): a register keeps one
(value, timestamp) pair; pair A beats pair B iff ts_A > ts_B, or the
timestamps are equal and value_A > value_B by string sorting rules.
Reference repo: jylis/repo_treg.pony:24-68.

TPU-native layout: the keyspace is three parallel vectors —
``ts[key] : uint64``, ``rank[key] : uint64`` (order-preserving 8-byte value
prefix, see ops/interner.py), and ``vid[key] : int64`` (interned value id,
-1 = unset). The value tie-break runs on-device via the rank; batches where
ts and rank are equal but vids differ (a prefix collision) are flagged and
resolved on host with full strings — correctness is exact, the device just
fast-paths the overwhelmingly common case.

Contract: one batch must contain at most one delta per key (the reference
coalesces per-key deltas per flush window, repo_gcount.pony:43-48 pattern);
use ``converge_many`` to fold several replica batches.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

UINT64 = jnp.uint64


class TRegState(NamedTuple):
    ts: jax.Array  # (K,) uint64; 0 when unset
    rank: jax.Array  # (K,) uint64 value-prefix rank; 0 when unset
    vid: jax.Array  # (K,) int64 interned value id; -1 when unset


def init(num_keys: int) -> TRegState:
    return TRegState(
        jnp.zeros((num_keys,), UINT64),
        jnp.zeros((num_keys,), UINT64),
        jnp.full((num_keys,), -1, jnp.int64),
    )


def _b_wins(
    ts_a: jax.Array, rank_a: jax.Array, vid_a: jax.Array,
    ts_b: jax.Array, rank_b: jax.Array, vid_b: jax.Array,
):
    """Where pair B strictly beats pair A, plus an on-host-tie flag.

    An unset register (vid -1, ts 0, rank 0) loses to any set pair: a set
    pair has either ts > 0 or a real value whose presence beats absence —
    encoded by treating vid >= 0 as a final presence tie-break.
    """
    wins = (ts_b > ts_a) | (
        (ts_b == ts_a)
        & ((rank_b > rank_a) | ((rank_b == rank_a) & (vid_a < 0) & (vid_b >= 0)))
    )
    tie = (ts_b == ts_a) & (rank_b == rank_a) & (vid_a >= 0) & (vid_b >= 0) & (vid_a != vid_b)
    return wins, tie


def converge_batch(
    state: TRegState,
    key_idx: jax.Array,
    d_ts: jax.Array,
    d_rank: jax.Array,
    d_vid: jax.Array,
) -> tuple[TRegState, jax.Array]:
    """Join one delta batch (unique keys): gather rows, compare, scatter.

    Returns (new_state, tie_mask); tie_mask (B,) bool marks rows whose
    winner must be decided on host by full string comparison.
    """
    cur_ts = state.ts[key_idx]
    cur_rank = state.rank[key_idx]
    cur_vid = state.vid[key_idx]
    wins, tie = _b_wins(cur_ts, cur_rank, cur_vid, d_ts, d_rank, d_vid)
    new_ts = jnp.where(wins, d_ts, cur_ts)
    new_rank = jnp.where(wins, d_rank, cur_rank)
    new_vid = jnp.where(wins, d_vid, cur_vid)
    return (
        TRegState(
            state.ts.at[key_idx].set(new_ts, mode="drop"),
            state.rank.at[key_idx].set(new_rank, mode="drop"),
            state.vid.at[key_idx].set(new_vid, mode="drop"),
        ),
        tie,
    )


def converge_many(
    state: TRegState,
    key_idx: jax.Array,
    d_ts: jax.Array,
    d_rank: jax.Array,
    d_vid: jax.Array,
) -> tuple[TRegState, jax.Array]:
    """Fold several replica batches: inputs are (N, B)-shaped; scans over N.

    Returns (state, tie_mask (N, B)). One compiled program for the whole
    anti-entropy round (BASELINE.json config 3: 1M keys, random-ts merge).
    """

    def step(st, batch):
        ki, ts, rk, vd = batch
        st, tie = converge_batch(st, ki, ts, rk, vd)
        return st, tie

    return jax.lax.scan(step, state, (key_idx, d_ts, d_rank, d_vid))


def set_batch(
    state: TRegState,
    key_idx: jax.Array,
    ts: jax.Array,
    rank: jax.Array,
    vid: jax.Array,
) -> tuple[TRegState, jax.Array]:
    """Local SET is lattice-identical to converging a delta (LWW join)."""
    return converge_batch(state, key_idx, ts, rank, vid)


def read(state: TRegState, key_idx: jax.Array):
    """GET for a batch of keys -> (ts, vid); vid -1 means nil reply."""
    return state.ts[key_idx], state.vid[key_idx]


def grow(state: TRegState, num_keys: int) -> TRegState:
    k = state.ts.shape[0]
    if num_keys == k:
        return state
    return TRegState(
        jnp.zeros((num_keys,), UINT64).at[:k].set(state.ts),
        jnp.zeros((num_keys,), UINT64).at[:k].set(state.rank),
        jnp.full((num_keys,), -1, jnp.int64).at[:k].set(state.vid),
    )
