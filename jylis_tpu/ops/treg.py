"""TREG: last-writer-wins timestamped register as batched TPU kernels.

Semantics (docs/_docs/types/treg.md:56-63): a register keeps one
(value, timestamp) pair; pair A beats pair B iff ts_A > ts_B, or the
timestamps are equal and value_A > value_B by string sorting rules.
Reference repo: jylis/repo_treg.pony:24-68.

TPU-native layout: the keyspace is parallel vectors — the u64 timestamp
and the u64 order-preserving value-prefix rank (ops/interner.py) each
stored as hi/lo u32 planes (XLA's u64 scatter emulation costs ~150 ms per
1M indices regardless of row width — measured; u32 scatters are ~15x
cheaper), plus ``vid[key] : int32`` (interned value id, -1 = unset). The
value tie-break runs on-device via the rank; batches where ts and rank are
equal but vids differ (a prefix collision) are flagged and resolved on
host with full strings — correctness is exact, the device just fast-paths
the overwhelmingly common case.

Contract: one batch must contain at most one delta per key (the reference
coalesces per-key deltas per flush window, repo_gcount.pony:43-48 pattern);
use ``converge_many`` to fold several replica batches.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

U32 = jnp.uint32
I32 = jnp.int32


class TRegState(NamedTuple):
    ts_hi: jax.Array  # (K,) uint32; 0 when unset
    ts_lo: jax.Array
    rank_hi: jax.Array  # (K,) uint32 value-prefix rank planes; 0 when unset
    rank_lo: jax.Array
    vid: jax.Array  # (K,) int32 interned value id; -1 when unset


def init(num_keys: int) -> TRegState:
    # distinct buffers: drains donate the state
    return TRegState(
        jnp.zeros((num_keys,), U32),
        jnp.zeros((num_keys,), U32),
        jnp.zeros((num_keys,), U32),
        jnp.zeros((num_keys,), U32),
        jnp.full((num_keys,), -1, I32),
    )


def _gt64(a_hi, a_lo, b_hi, b_lo):
    """a > b over hi/lo u32 planes."""
    return (a_hi > b_hi) | ((a_hi == b_hi) & (a_lo > b_lo))


def _eq64(a_hi, a_lo, b_hi, b_lo):
    return (a_hi == b_hi) & (a_lo == b_lo)


def _b_wins(a, b):
    """Where pair B strictly beats pair A, plus an on-host-tie flag.

    a/b: tuples (ts_hi, ts_lo, rank_hi, rank_lo, vid). An unset register
    (vid -1, zeros) loses to any set pair: a set pair has either ts > 0 or
    a real value whose presence beats absence — encoded by treating
    vid >= 0 as a final presence tie-break.
    """
    a_th, a_tl, a_rh, a_rl, a_v = a
    b_th, b_tl, b_rh, b_rl, b_v = b
    ts_eq = _eq64(a_th, a_tl, b_th, b_tl)
    rank_eq = _eq64(a_rh, a_rl, b_rh, b_rl)
    wins = _gt64(b_th, b_tl, a_th, a_tl) | (
        ts_eq
        & (_gt64(b_rh, b_rl, a_rh, a_rl) | (rank_eq & (a_v < 0) & (b_v >= 0)))
    )
    tie = ts_eq & rank_eq & (a_v >= 0) & (b_v >= 0) & (a_v != b_v)
    return wins, tie


def converge_batch(
    state: TRegState,
    key_idx: jax.Array,
    d_ts_hi: jax.Array,
    d_ts_lo: jax.Array,
    d_rank_hi: jax.Array,
    d_rank_lo: jax.Array,
    d_vid: jax.Array,
) -> tuple[TRegState, jax.Array]:
    """Join one delta batch (unique keys): gather rows, compare, scatter.

    Returns (new_state, tie_mask); tie_mask (B,) bool marks rows whose
    winner must be decided on host by full string comparison.
    """
    cur = tuple(plane[key_idx] for plane in state)
    d = (d_ts_hi, d_ts_lo, d_rank_hi, d_rank_lo, d_vid)
    wins, tie = _b_wins(cur, d)
    new = [jnp.where(wins, dv, cv) for dv, cv in zip(d, cur)]
    return (
        TRegState(
            *(
                plane.at[key_idx].set(nv, mode="drop", unique_indices=True)
                for plane, nv in zip(state, new)
            )
        ),
        tie,
    )


def converge_dense(
    state: TRegState,
    d_ts_hi: jax.Array,
    d_ts_lo: jax.Array,
    d_rank_hi: jax.Array,
    d_rank_lo: jax.Array,
    d_vid: jax.Array,
) -> tuple[TRegState, jax.Array]:
    """Full-keyspace elementwise LWW join — the dense fast path.

    The delta arrays are in dense key order ((K,) each, same length as the
    state); rows with no delta carry the lattice identity (0, 0, 0, 0, -1),
    which never wins and never ties. No gather, no scatter: when a batch
    covers most of the keyspace (a full anti-entropy sweep — the
    BASELINE.json north-star shape), this streams each plane exactly once
    instead of paying random-access gathers and scatters twice per plane.

    Returns (new_state, tie_mask (K,)).
    """
    d = (d_ts_hi, d_ts_lo, d_rank_hi, d_rank_lo, d_vid)
    wins, tie = _b_wins(tuple(state), d)
    return (
        TRegState(*(jnp.where(wins, dv, cv) for dv, cv in zip(d, state))),
        tie,
    )


def converge_many(
    state: TRegState,
    key_idx: jax.Array,
    d_ts_hi: jax.Array,
    d_ts_lo: jax.Array,
    d_rank_hi: jax.Array,
    d_rank_lo: jax.Array,
    d_vid: jax.Array,
) -> tuple[TRegState, jax.Array]:
    """Fold several replica batches: inputs are (N, B)-shaped; scans over N.

    Returns (state, tie_mask (N, B)). One compiled program for a whole
    multi-batch anti-entropy round. NOT on the serving path: the repo
    coalesces concurrent deltas per key host-side with the exact LWW rule
    (full strings, no rank-collision ambiguity — repo_treg.py:_write), so
    a drain always carries one winner per key; this kernel exists for
    bench/offline folds where batches arrive pre-formed.
    """

    def step(st, batch):
        ki, th, tl, rh, rl, vd = batch
        st, tie = converge_batch(st, ki, th, tl, rh, rl, vd)
        return st, tie

    return jax.lax.scan(
        step, state, (key_idx, d_ts_hi, d_ts_lo, d_rank_hi, d_rank_lo, d_vid)
    )


def set_batch(state, key_idx, ts_hi, ts_lo, rank_hi, rank_lo, vid):
    """Local SET is lattice-identical to converging a delta (LWW join)."""
    return converge_batch(state, key_idx, ts_hi, ts_lo, rank_hi, rank_lo, vid)


def read(state: TRegState, key_idx: jax.Array):
    """GET for a batch of keys -> (ts_hi, ts_lo, vid); vid -1 = nil reply."""
    return state.ts_hi[key_idx], state.ts_lo[key_idx], state.vid[key_idx]


def grow(state: TRegState, num_keys: int) -> TRegState:
    k = state.vid.shape[0]
    if num_keys == k:
        return state
    return TRegState(
        jnp.zeros((num_keys,), U32).at[:k].set(state.ts_hi),
        jnp.zeros((num_keys,), U32).at[:k].set(state.ts_lo),
        jnp.zeros((num_keys,), U32).at[:k].set(state.rank_hi),
        jnp.zeros((num_keys,), U32).at[:k].set(state.rank_lo),
        jnp.full((num_keys,), -1, I32).at[:k].set(state.vid),
    )
