"""UJSON ORSWOT join as batched device kernels.

The host lattice (`ops/ujson_host.py`) is authoritative for serving —
documents are small and pointer-heavy. What DOES tensorise is the
anti-entropy fan-in (docs/_docs/types/ujson.md:134-182 semantics,
reference loop repo_ujson.pony:96-110): joining many deltas into many
replica documents, where the per-entry set operations dominate. This
module represents a batch of documents as padded per-row tensors and
implements the ORSWOT join as sorted-set ops:

* ``dots (B, L)`` — each entry's causal dot packed as
  ``(replica_col << shift) | seq``, sorted ascending per row, pad-filled.
  Replica ids (64-bit hashes) are interned to columns on the host,
  exactly like the counter repos. The dtype is ADAPTIVE per batch:
  when every seq fits in ``31 - ceil(log2 R)`` bits the dots pack into
  native-sortable **int32** (TPUs have no 64-bit datapath; u64 sorts
  emulate compares and dominated the join's cost when this module used
  them unconditionally), otherwise uint64 with shift 32. The shift is a
  static jit parameter, so each layout compiles its own kernels.
* ``pay (B, L) int32`` — interned (path, value-token) payload id; -1 pad.
  Dots name payloads immutably (a dot's (path, value) never changes), so
  the join only moves ids and the host interner resolves them back.
* ``vv (B, R) uint32`` — per-replica-column contiguous causal max.
* ``cloud (B, C)`` — context dots beyond the vv, sorted, pad-filled (same
  dtype as ``dots``). Device joins never compact cloud→vv (that
  bookkeeping is sequential and host-cheap); coverage stays exact
  because ``contains`` checks the union vv ∪ cloud either way.

Join of rows a, b (the documented add-wins rule):
  keep an a-entry iff it is also in b, or b's context never observed it;
  add a b-entry iff a doesn't hold it and a's context never observed it.
Membership tests are ``searchsorted`` probes on the sorted dot rows;
coverage is a vv gather + compare plus a cloud probe; the surviving
entries merge by one concat + sort per side pair. Everything is static
shape: output widths are the (padded) sums of the input widths, and the
host re-buckets between rounds (`compact`).

``fold_deltas`` is where the TPU earns its keep: the join is associative
and commutative, so N deltas fold in ceil(log_8 N) batched device calls
(8 rows reduce per launch — dispatch latency over the tunneled chip is
per-launch) instead of N sequential host merges, and the folded delta
then joins every replica in ONE batched call (`bench.py --config
ujson-32`).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.batching import bucket

U64 = jnp.uint64
U32 = jnp.uint32
I32 = jnp.int32

PAD64 = np.uint64(0xFFFFFFFFFFFFFFFF)
PAD32 = np.int32(0x7FFFFFFF)


def _pad_of(dtype) -> np.generic:
    return PAD32 if np.dtype(dtype) == np.int32 else PAD64


class DocBatch(NamedTuple):
    """B documents as padded device tensors (see module docstring)."""

    dots: jax.Array  # (B, L) int32 or uint64, sorted per row, pad-filled
    pay: jax.Array  # (B, L) int32, -1 pad
    vv: jax.Array  # (B, R) uint32
    cloud: jax.Array  # (B, C) same dtype as dots, sorted, pad-filled


def _member(sorted_row, queries):
    """For each query, is it present in the sorted (pad-filled) row?"""
    idx = jnp.searchsorted(sorted_row, queries)
    idx = jnp.minimum(idx, sorted_row.shape[-1] - 1)
    return sorted_row[idx] == queries


def _covered(vv, cloud, dots, shift):
    """ctx.contains for each dot: seq <= vv[rid] or dot in cloud.

    The vv lookup runs as a per-replica-column mask reduction instead of
    a computed-index gather (pathologically slow on this TPU); R is
    small and static."""
    dt = dots.dtype
    r = vv.shape[-1]
    rid = (dots >> dt.type(shift)).astype(I32)
    seq = (dots & dt.type((1 << shift) - 1)).astype(U32)
    rid = jnp.minimum(rid, r - 1)  # pads decode out of range; callers mask
    colmask = rid[None, :] == jnp.arange(r, dtype=I32)[:, None]  # (R, W)
    vvd = jnp.sum(jnp.where(colmask, vv[:, None], U32(0)), axis=0, dtype=U32)
    return (seq <= vvd) | _member(cloud, dots)


def _sortmerge(row_a, pay_a, row_b, pay_b):
    """Merge two masked rows into one sorted row (pays ride along)."""
    dots = jnp.concatenate([row_a, row_b], axis=-1)
    pays = jnp.concatenate([pay_a, pay_b], axis=-1)
    order = jnp.argsort(dots)
    return dots[order], pays[order]


def _join_row(
    a_dots, a_pay, a_vv, a_cloud, b_dots, b_pay, b_vv, b_cloud,
    shift, sort_output=True,
):
    pad = _pad_of(a_dots.dtype)
    valid_a = a_dots != pad
    valid_b = b_dots != pad
    keep_a = valid_a & (
        _member(b_dots, a_dots) | ~_covered(b_vv, b_cloud, a_dots, shift)
    )
    # no duplicate survivors: an added b-entry is by definition not in a
    add_b = valid_b & ~_member(a_dots, b_dots) & ~_covered(
        a_vv, a_cloud, b_dots, shift
    )
    ka_dots = jnp.where(keep_a, a_dots, pad)
    ka_pay = jnp.where(keep_a, a_pay, -1)
    ab_dots = jnp.where(add_b, b_dots, pad)
    ab_pay = jnp.where(add_b, b_pay, -1)
    if sort_output:
        dots, pay = _sortmerge(ka_dots, ka_pay, ab_dots, ab_pay)
    else:
        # the sort is the join's dominant cost; a FINAL join whose output
        # feeds no further searchsorted can skip it
        dots = jnp.concatenate([ka_dots, ab_dots], axis=-1)
        pay = jnp.concatenate([ka_pay, ab_pay], axis=-1)
    vv = jnp.maximum(a_vv, b_vv)
    # context union; duplicates are harmless for coverage but dedup keeps
    # growth linear: sort, blank repeats, resort
    cl = jnp.sort(jnp.concatenate([a_cloud, b_cloud], axis=-1))
    dup = jnp.concatenate([jnp.zeros((1,), bool), cl[1:] == cl[:-1]])
    cloud = jnp.sort(jnp.where(dup, pad, cl))
    return dots, pay, vv, cloud


@partial(jax.jit, static_argnames=("shift", "sort_output"))
def join_batch(
    a: DocBatch, b: DocBatch, shift: int = 32, sort_output: bool = True
) -> DocBatch:
    """Row-wise ORSWOT join of two document batches (row i joins row i).

    Output widths are the sums of the input widths (static shapes); use
    `compact` on the host to re-bucket when they grow past the live size.
    ``sort_output=False`` only when nothing will searchsorted-probe the
    result (e.g. the last join before a host read-back).
    """
    return DocBatch(
        *jax.vmap(partial(_join_row, shift=shift, sort_output=sort_output))(
            a.dots, a.pay, a.vv, a.cloud, b.dots, b.pay, b.vv, b.cloud
        )
    )


FOLD_ARITY = 8  # rows folded per unrolled fold level


def _join_inside(a: DocBatch, b: DocBatch, shift: int) -> DocBatch:
    return DocBatch(
        *jax.vmap(partial(_join_row, shift=shift))(
            a.dots, a.pay, a.vv, a.cloud, b.dots, b.pay, b.vv, b.cloud
        )
    )


def _empty_rows(batch: DocBatch, n: int) -> DocBatch:
    """n identity rows (no entries, empty context) at batch's widths."""
    pad = _pad_of(batch.dots.dtype)
    return DocBatch(
        jnp.full((n, batch.dots.shape[-1]), pad, batch.dots.dtype),
        jnp.full((n, batch.pay.shape[-1]), -1, I32),
        jnp.zeros((n, batch.vv.shape[-1]), U32),
        jnp.full((n, batch.cloud.shape[-1]), pad, batch.cloud.dtype),
    )


def _fold_body(batch: DocBatch, shift: int) -> DocBatch:
    """Traceable full fold: the level loop unrolls at trace time (shapes
    are static), so however many levels, the caller pays ONE dispatch."""
    while batch.dots.shape[0] > 1:
        n = batch.dots.shape[0]
        k = min(FOLD_ARITY, 1 << (n - 1).bit_length())
        if n % k:
            pad = _empty_rows(batch, k - n % k)
            batch = DocBatch(
                *(jnp.concatenate([p, q], axis=0) for p, q in zip(batch, pad))
            )
            n = batch.dots.shape[0]
        step = n // k
        items = [
            DocBatch(*(p[i * step : (i + 1) * step] for p in batch))
            for i in range(k)
        ]
        while len(items) > 1:
            items = [
                _join_inside(items[i], items[i + 1], shift)
                for i in range(0, len(items), 2)
            ]
        batch = items[0]
    return batch


@partial(jax.jit, static_argnames=("shift",))
def fold_deltas(batch: DocBatch, shift: int = 32) -> DocBatch:
    """Fold all B rows into ONE document in a single device dispatch (the
    join is associative and commutative, so any fold shape converges
    identically; FOLD_ARITY-wide levels keep the trace shallow)."""
    return _fold_body(batch, shift)


@partial(jax.jit, static_argnames=("shift",))
def fold_segments(batch: DocBatch, shift: int = 32) -> DocBatch:
    """Segmented multi-key fan-in: planes shaped (K, D, W); every key's D
    delta rows fold to ONE document, all keys in the SAME dispatch — K
    keys' anti-entropy fan-ins for a single launch's latency. The
    reference converges one delta at a time per key
    (repo_ujson.pony:96-110); here the whole drain is one device program.
    The key axis is a plain vmap over the single-key fold body, so the
    two paths can never diverge."""
    folded = jax.vmap(lambda b: _fold_body(b, shift))(batch)
    return DocBatch(*(p[:, 0] for p in folded))


def encode_doc_groups(
    groups, rid_cols: dict[int, int], pay_ids, n_rep: int, shift: int = 32
) -> DocBatch:
    """Pack K keys' delta lists into the (K, D, W) grid `fold_segments`
    takes; short groups pad with identity docs (the join's neutral
    element), so the fold result per key is exactly the fold of its own
    deltas."""
    from .ujson_host import UJSON

    d = bucket(max((len(g) for g in groups), default=1), 1)
    empty = UJSON()
    flat = []
    for g in groups:
        flat.extend(g)
        flat.extend([empty] * (d - len(g)))
    b = _encode_docs_np(flat, rid_cols, pay_ids, n_rep, shift=shift)
    return DocBatch(
        *(
            jnp.asarray(p.reshape((len(groups), d) + p.shape[1:]))
            for p in b
        )
    )


def _tile(delta_row: DocBatch, b: int) -> DocBatch:
    return DocBatch(
        *(jnp.broadcast_to(p, (b,) + p.shape[1:]) for p in delta_row)
    )


def broadcast_join(
    replicas: DocBatch,
    delta_row: DocBatch,
    shift: int = 32,
    sort_output: bool = True,
) -> DocBatch:
    """Join ONE folded delta into every replica row in one batched call."""
    return join_batch(
        replicas,
        _tile(delta_row, replicas.dots.shape[0]),
        shift=shift,
        sort_output=sort_output,
    )


@partial(jax.jit, static_argnames=("shift", "sort_output"))
def fold_and_broadcast(
    replicas: DocBatch,
    deltas: DocBatch,
    shift: int = 32,
    sort_output: bool = False,
) -> DocBatch:
    """The whole anti-entropy fan-in as ONE device program: fold all
    delta rows, then join the result into every replica row. On a
    tunneled chip the dominant cost is per-dispatch latency, so the
    fold levels and the broadcast must not be separate launches."""
    folded = _fold_body(deltas, shift)
    b = replicas.dots.shape[0]
    return DocBatch(
        *jax.vmap(partial(_join_row, shift=shift, sort_output=sort_output))(
            replicas.dots,
            replicas.pay,
            replicas.vv,
            replicas.cloud,
            *_tile(folded, b),
        )
    )


# ---- host-side encode / decode / compaction --------------------------------


def plan_shift(docs, n_rep: int) -> int:
    """Pick the dot layout for a batch: int32 with the smallest workable
    shift when every seq fits (native TPU sorts), else the u64/32 layout.
    The all-ones seq is reserved in the narrow layout: the top replica
    column with an all-ones seq would pack to exactly PAD32 and vanish
    as padding.
    """
    seq_bits = narrow_shift(n_rep)
    wide = (1 << seq_bits) - 1
    # per-container max() builtins instead of per-item Python compares:
    # this scan runs on every drain, right next to the encode hot loop
    for doc in docs:
        if doc.entries and max(s for _, s in doc.entries) >= wide:
            return 32
        vv = doc.ctx.vv
        if vv and max(vv.values()) >= wide:
            return 32
        cl = doc.ctx.cloud
        if cl and max(s for _, s in cl) >= wide:
            return 32
    return seq_bits


def _slot_cols(lens: np.ndarray) -> np.ndarray:
    """Per-row slot columns for variable-length rows, vectorised:
    [0..lens[0]) ++ [0..lens[1]) ++ ... with no Python per-row loop."""
    total = int(lens.sum())
    starts = np.cumsum(lens) - lens
    return np.arange(total, dtype=np.int64) - np.repeat(starts, lens)


def narrow_shift(n_rep: int) -> int:
    """The int32 layout's shift for this replica-column budget."""
    return 31 - max(int(n_rep - 1).bit_length(), 1)


def _auto_shift_encode(encode_one, n_rep: int, prefer: int | None):
    """Shared narrow-first/wide-fallback layout policy: encode at the
    narrow int32 layout, falling back to u64/32 when any seq (or pad
    collision) overflows mid-pass. The encode's own validity checks
    subsume a separate `plan_shift` scan, which measured as ~30% of the
    whole fan-in path; retrying is safe because rid_cols/pay_ids updates
    are idempotent setdefaults. ``prefer=32`` skips the narrow attempt —
    callers memoise it (e.g. the serving repo) so a steady-state wide
    workload doesn't pay a doomed narrow pass on every drain."""
    shift = 32 if prefer == 32 else narrow_shift(n_rep)
    try:
        return encode_one(shift), shift
    except OverflowError:
        if shift == 32:
            raise  # genuinely un-encodable (caller falls back to host)
        return encode_one(32), 32


def encode_docs_auto(docs, rid_cols, pay_ids, n_rep, prefer=None):
    """`encode_docs` under the narrow-first layout policy; returns
    (batch, shift)."""
    return _auto_shift_encode(
        lambda sh: encode_docs(docs, rid_cols, pay_ids, n_rep, shift=sh),
        n_rep,
        prefer,
    )


def encode_doc_lists_auto(lists, rid_cols, pay_ids, n_rep, prefer=None):
    """Several doc lists encoded under ONE shared layout (joins require
    identical shifts); returns (batches, shift)."""
    return _auto_shift_encode(
        lambda sh: [
            encode_docs(docs, rid_cols, pay_ids, n_rep, shift=sh)
            for docs in lists
        ],
        n_rep,
        prefer,
    )


def encode_doc_groups_auto(groups, rid_cols, pay_ids, n_rep, prefer=None):
    """`encode_doc_groups` under the narrow-first layout policy; returns
    (batch, shift)."""
    return _auto_shift_encode(
        lambda sh: encode_doc_groups(groups, rid_cols, pay_ids, n_rep, shift=sh),
        n_rep,
        prefer,
    )


def _encode_docs_np(
    docs, rid_cols: dict[int, int], pay_ids, n_rep: int, shift: int = 32
) -> DocBatch:
    """`encode_docs` core, returning host numpy planes (callers that
    reshape or concatenate do it host-side, then transfer ONCE — a jnp
    reshape is a device dispatch, ruinous over a tunneled chip).

    This is the serving path's host bottleneck (the device fold is ~free
    next to it), so the loop accumulates flat lists only — no per-doc
    allocations, no sorting of singleton rows — and every plane fills
    with one fancy-index scatter built from vectorised row/column
    indices."""
    seq_cap = 1 << shift
    setd = rid_cols.setdefault
    b = len(docs)
    d_lens = np.zeros(b, np.int64)
    c_lens = np.zeros(b, np.int64)
    dv: list[int] = []
    pv: list[int] = []
    cv: list[int] = []
    vv_ri: list[int] = []
    vv_ci: list[int] = []
    vv_sv: list[int] = []
    for i, doc in enumerate(docs):
        n0 = len(dv)
        for (rid, seq), pt in doc.entries.items():
            col = setd(rid, len(rid_cols))
            if seq >= seq_cap:
                raise OverflowError(f"seq {seq} needs a wider layout than {shift}")
            dv.append((col << shift) | seq)
            pv.append(pay_ids(*pt))
        k = len(dv) - n0
        if k > 1:  # rows must be dot-sorted; singletons already are
            seg = sorted(zip(dv[n0:], pv[n0:]))
            dv[n0:] = [d for d, _ in seg]
            pv[n0:] = [p for _, p in seg]
        d_lens[i] = k
        for rid, s in doc.ctx.vv.items():
            col = setd(rid, len(rid_cols))
            if s >= seq_cap or s > 0xFFFFFFFF:
                # clamping would SHRINK coverage and resurrect removed
                # entries — refuse; callers fall back to the host lattice
                raise OverflowError(f"vv seq {s} needs a wider layout")
            vv_ri.append(i)
            vv_ci.append(col)
            vv_sv.append(s)
        n0c = len(cv)
        for rid, seq in doc.ctx.cloud:
            col = setd(rid, len(rid_cols))
            if seq >= seq_cap:
                raise OverflowError(f"seq {seq} needs a wider layout than {shift}")
            cv.append((col << shift) | seq)
        kc = len(cv) - n0c
        if kc > 1:
            cv[n0c:] = sorted(cv[n0c:])
        c_lens[i] = kc
    dtype = np.int32 if shift < 32 else np.uint64
    pad = _pad_of(dtype)
    if len(rid_cols) > n_rep:
        raise ValueError(f"n_rep {n_rep} too small for {len(rid_cols)} replicas")
    wl = bucket(max(int(d_lens.max()) if b else 0, 1), 4)
    wc = bucket(max(int(c_lens.max()) if b else 0, 1), 4)
    dots = np.full((b, wl), pad, dtype)
    pay = np.full((b, wl), -1, np.int32)
    vv = np.zeros((b, n_rep), np.uint32)
    cloud = np.full((b, wc), pad, dtype)
    if dv:
        dvals = np.asarray(dv, dtype)
        if bool((dvals == pad).any()):
            raise OverflowError("dot collides with the pad sentinel")
        rows_i = np.repeat(np.arange(b), d_lens)
        cols_i = _slot_cols(d_lens)
        dots[rows_i, cols_i] = dvals
        pay[rows_i, cols_i] = np.asarray(pv, np.int32)
    if vv_ri:
        vv[np.asarray(vv_ri, np.int64), np.asarray(vv_ci, np.int64)] = np.asarray(
            vv_sv, np.uint32
        )
    if cv:
        cvals = np.asarray(cv, dtype)
        if bool((cvals == pad).any()):
            raise OverflowError("dot collides with the pad sentinel")
        cloud[np.repeat(np.arange(b), c_lens), _slot_cols(c_lens)] = cvals
    return DocBatch(dots, pay, vv, cloud)


def encode_docs(
    docs, rid_cols: dict[int, int], pay_ids, n_rep: int, shift: int = 32
) -> DocBatch:
    """Pack host `UJSON` documents into one DocBatch at the given layout
    (see `plan_shift`).

    rid_cols: replica-id -> column (shared, grows on host like the
    counter repos' _rids). pay_ids: callable (path, token) -> int32 id.
    """
    return DocBatch(
        *(jnp.asarray(p) for p in _encode_docs_np(docs, rid_cols, pay_ids, n_rep, shift))
    )


def decode_batch(batch: DocBatch, cols_rid, pay_lookup, shift: int = 32) -> list:
    """Unpack every row back into host `UJSON` docs (reads/verification).

    cols_rid: column -> replica id; pay_lookup: id -> (path, token).
    Each plane transfers device->host exactly ONCE — per-row pulls would
    pay the (tunneled) dispatch latency B×4 times.
    """
    from .ujson_host import UJSON

    pad = _pad_of(np.asarray(batch.dots).dtype)
    mask = (1 << shift) - 1
    all_dots = np.asarray(batch.dots)
    all_pays = np.asarray(batch.pay)
    all_vv = np.asarray(batch.vv)
    all_cloud = np.asarray(batch.cloud)
    docs = []
    for row in range(all_dots.shape[0]):
        doc = UJSON()
        for d, p in zip(all_dots[row], all_pays[row]):
            if d == pad:
                continue
            d = int(d)
            doc.entries[(cols_rid[d >> shift], d & mask)] = pay_lookup(int(p))
        for col, s in enumerate(all_vv[row]):
            if s:
                doc.ctx.vv[cols_rid[col]] = int(s)
        for c in all_cloud[row]:
            if c != pad:
                c = int(c)
                doc.ctx.cloud.add((cols_rid[c >> shift], c & mask))
        doc.ctx.compact()
        docs.append(doc)
    return docs


def decode_doc(batch: DocBatch, row: int, cols_rid, pay_lookup, shift: int = 32):
    """Single-row convenience wrapper over `decode_batch`."""
    one = DocBatch(*(p[row : row + 1] for p in batch))
    return decode_batch(one, cols_rid, pay_lookup, shift=shift)[0]


def compact(batch: DocBatch) -> DocBatch:
    """Host-side re-bucket: drop all-pad columns the joins accumulated."""
    dots = np.asarray(batch.dots)
    cloud = np.asarray(batch.cloud)
    pad = _pad_of(dots.dtype)
    live_l = int((dots != pad).sum(axis=1).max()) if dots.size else 1
    live_c = int((cloud != pad).sum(axis=1).max()) if cloud.size else 1
    wl, wc = bucket(max(live_l, 1), 4), bucket(max(live_c, 1), 4)
    return DocBatch(
        jnp.asarray(dots[:, :wl]),
        jnp.asarray(np.asarray(batch.pay)[:, :wl]),
        batch.vv,
        jnp.asarray(cloud[:, :wc]),
    )
