"""UJSON ORSWOT join as batched device kernels.

The host lattice (`ops/ujson_host.py`) is authoritative for serving —
documents are small and pointer-heavy. What DOES tensorise is the
anti-entropy fan-in (docs/_docs/types/ujson.md:134-182 semantics,
reference loop repo_ujson.pony:96-110): joining many deltas into many
replica documents, where the per-entry set operations dominate. This
module represents a batch of documents as padded per-row tensors and
implements the ORSWOT join as sorted-set ops:

* ``dots (B, L) uint64`` — each entry's causal dot packed as
  ``(replica_col << 32) | seq``, sorted ascending per row, ``PAD``
  (2^64-1) in unused slots. Replica ids (64-bit hashes) are interned to
  columns on the host, exactly like the counter repos; seqs are bounded
  to u32 on the device path (the host lattice keeps unbounded ints — a
  document that ever exceeds 2^32-1 mutations from one replica stays on
  the host path).
* ``pay (B, L) int32`` — interned (path, value-token) payload id; -1 pad.
  Dots name payloads immutably (a dot's (path, value) never changes), so
  the join only moves ids and the host interner resolves them back.
* ``vv (B, R) uint32`` — per-replica-column contiguous causal max.
* ``cloud (B, C) uint64`` — context dots beyond the vv, sorted, PAD pad.
  Device joins never compact cloud→vv (that bookkeeping is sequential
  and host-cheap); coverage stays exact because ``contains`` checks the
  union vv ∪ cloud either way.

Join of rows a, b (the documented add-wins rule):
  keep an a-entry iff it is also in b, or b's context never observed it;
  add a b-entry iff a doesn't hold it and a's context never observed it.
Membership tests are ``searchsorted`` probes on the sorted dot rows;
coverage is a vv gather + compare plus a cloud probe; the surviving
entries merge by one concat + sort per side pair. Everything is static
shape: output widths are the (padded) sums of the input widths, and the
host re-buckets between rounds.

``fold_deltas`` is where the TPU earns its keep: the join is associative
and commutative, so N deltas fold pairwise in ceil(log2 N) batched
device calls instead of N sequential host merges, and the folded delta
then joins every replica in ONE batched call (`bench.py --config
ujson-32`).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.batching import bucket

U64 = jnp.uint64
U32 = jnp.uint32
I32 = jnp.int32

PAD = np.uint64(0xFFFFFFFFFFFFFFFF)


class DocBatch(NamedTuple):
    """B documents as padded device tensors (see module docstring)."""

    dots: jax.Array  # (B, L) uint64, sorted per row, PAD-padded
    pay: jax.Array  # (B, L) int32, -1 pad
    vv: jax.Array  # (B, R) uint32
    cloud: jax.Array  # (B, C) uint64, sorted per row, PAD-padded


def pack_dot(rid_col: int, seq: int) -> int:
    return (rid_col << 32) | seq


def unpack_dot(dot: int) -> tuple[int, int]:
    return dot >> 32, dot & 0xFFFFFFFF


def _member(sorted_row, queries):
    """For each query, is it present in the sorted (PAD-padded) row?"""
    idx = jnp.searchsorted(sorted_row, queries)
    idx = jnp.minimum(idx, sorted_row.shape[-1] - 1)
    return sorted_row[idx] == queries


def _covered(vv, cloud, dots):
    """ctx.contains for each dot: seq <= vv[rid] or dot in cloud."""
    rid = (dots >> jnp.uint64(32)).astype(I32)
    seq = (dots & jnp.uint64(0xFFFFFFFF)).astype(U32)
    # PAD rows gather rid 2^31-ish; clamp and rely on callers masking pads
    rid = jnp.minimum(rid, vv.shape[-1] - 1)
    return (seq <= vv[rid]) | _member(cloud, dots)


def _sortmerge(row_a, pay_a, row_b, pay_b):
    """Merge two masked rows into one sorted row (pays ride along)."""
    dots = jnp.concatenate([row_a, row_b], axis=-1)
    pays = jnp.concatenate([pay_a, pay_b], axis=-1)
    order = jnp.argsort(dots)
    return dots[order], pays[order]


def _join_row(a_dots, a_pay, a_vv, a_cloud, b_dots, b_pay, b_vv, b_cloud):
    valid_a = a_dots != PAD
    valid_b = b_dots != PAD
    keep_a = valid_a & (
        _member(b_dots, a_dots) | ~_covered(b_vv, b_cloud, a_dots)
    )
    # no duplicate survivors: an added b-entry is by definition not in a
    add_b = valid_b & ~_member(a_dots, b_dots) & ~_covered(a_vv, a_cloud, b_dots)
    dots, pay = _sortmerge(
        jnp.where(keep_a, a_dots, PAD),
        jnp.where(keep_a, a_pay, -1),
        jnp.where(add_b, b_dots, PAD),
        jnp.where(add_b, b_pay, -1),
    )
    vv = jnp.maximum(a_vv, b_vv)
    # context union; duplicates are harmless for coverage but dedup keeps
    # growth linear: sort, blank repeats, resort
    cl = jnp.sort(jnp.concatenate([a_cloud, b_cloud], axis=-1))
    dup = jnp.concatenate([jnp.zeros((1,), bool), cl[1:] == cl[:-1]])
    cloud = jnp.sort(jnp.where(dup, PAD, cl))
    return dots, pay, vv, cloud


@jax.jit
def join_batch(a: DocBatch, b: DocBatch) -> DocBatch:
    """Row-wise ORSWOT join of two document batches (row i joins row i).

    Output widths are the sums of the input widths (static shapes); use
    `compact` on the host to re-bucket when they grow past the live size.
    """
    return DocBatch(
        *jax.vmap(_join_row)(
            a.dots, a.pay, a.vv, a.cloud, b.dots, b.pay, b.vv, b.cloud
        )
    )


def fold_deltas(batch: DocBatch) -> DocBatch:
    """Fold all B rows into ONE document by pairwise tree join —
    ceil(log2 B) batched device calls for a B-delta anti-entropy fan-in.
    """
    while batch.dots.shape[0] > 1:
        n = batch.dots.shape[0]
        half = n // 2
        a = DocBatch(*(p[:half] for p in batch))
        b = DocBatch(*(p[half : 2 * half] for p in batch))
        joined = join_batch(a, b)
        if n % 2:
            joined = DocBatch(
                *(
                    jnp.concatenate([jp, _pad_to(lp[-1:], jp.shape[-1], pad)], axis=0)
                    for jp, lp, pad in zip(
                        joined, batch, (PAD, np.int32(-1), None, PAD)
                    )
                )
            )
        batch = joined
    return batch


def _pad_to(row, width, pad):
    cur = row.shape[-1]
    if cur == width:
        return row
    if pad is None:  # vv plane: widths never change
        return row
    fill = jnp.full(row.shape[:-1] + (width - cur,), pad, row.dtype)
    return jnp.concatenate([row, fill], axis=-1)


def broadcast_join(replicas: DocBatch, delta_row: DocBatch) -> DocBatch:
    """Join ONE folded delta into every replica row in one batched call."""
    b = replicas.dots.shape[0]
    tiled = DocBatch(*(jnp.broadcast_to(p, (b,) + p.shape[1:]) for p in delta_row))
    return join_batch(replicas, tiled)


# ---- host-side encode / decode / compaction --------------------------------


def encode_docs(docs, rid_cols: dict[int, int], pay_ids, n_rep: int) -> DocBatch:
    """Pack host `UJSON` documents into one DocBatch.

    rid_cols: replica-id -> column (shared, grows on host like the
    counter repos' _rids). pay_ids: callable (path, token) -> int32 id.
    """
    rows = []
    for doc in docs:
        dots = []
        for (rid, seq), (path, token) in doc.entries.items():
            col = rid_cols.setdefault(rid, len(rid_cols))
            if seq > 0xFFFFFFFF:
                raise OverflowError("device path bounds seqs to u32")
            dots.append((pack_dot(col, seq), pay_ids(path, token)))
        vv = np.zeros(n_rep, np.uint32)
        for rid, s in doc.ctx.vv.items():
            col = rid_cols.setdefault(rid, len(rid_cols))
            vv[col] = min(s, 0xFFFFFFFF)
        cloud = []
        for rid, seq in doc.ctx.cloud:
            col = rid_cols.setdefault(rid, len(rid_cols))
            cloud.append(pack_dot(col, seq))
        rows.append((sorted(dots), vv, sorted(cloud)))
    if len(rid_cols) > n_rep:
        raise ValueError(f"n_rep {n_rep} too small for {len(rid_cols)} replicas")
    wl = bucket(max((len(r[0]) for r in rows), default=1), 4)
    wc = bucket(max((len(r[2]) for r in rows), default=1), 4)
    b = len(rows)
    dots = np.full((b, wl), PAD, np.uint64)
    pay = np.full((b, wl), -1, np.int32)
    vv = np.zeros((b, n_rep), np.uint32)
    cloud = np.full((b, wc), PAD, np.uint64)
    for i, (drow, vrow, crow) in enumerate(rows):
        for j, (d, p) in enumerate(drow):
            dots[i, j] = d
            pay[i, j] = p
        vv[i] = vrow
        for j, c in enumerate(crow):
            cloud[i, j] = c
    return DocBatch(
        jnp.asarray(dots), jnp.asarray(pay), jnp.asarray(vv), jnp.asarray(cloud)
    )


def decode_doc(batch: DocBatch, row: int, cols_rid, pay_lookup):
    """Unpack one row back into a host `UJSON` (for reads / verification).

    cols_rid: column -> replica id; pay_lookup: id -> (path, token).
    """
    from .ujson_host import UJSON

    doc = UJSON()
    dots = np.asarray(batch.dots[row])
    pays = np.asarray(batch.pay[row])
    for d, p in zip(dots, pays):
        if d == PAD:
            continue
        col, seq = unpack_dot(int(d))
        doc.entries[(cols_rid[col], seq)] = pay_lookup(int(p))
    vv = np.asarray(batch.vv[row])
    for col, s in enumerate(vv):
        if s:
            doc.ctx.vv[cols_rid[col]] = int(s)
    for c in np.asarray(batch.cloud[row]):
        if c != PAD:
            col, seq = unpack_dot(int(c))
            doc.ctx.cloud.add((cols_rid[col], seq))
    doc.ctx.compact()
    return doc


def compact(batch: DocBatch) -> DocBatch:
    """Host-side re-bucket: drop all-pad columns the joins accumulated."""
    dots = np.asarray(batch.dots)
    cloud = np.asarray(batch.cloud)
    live_l = int((dots != PAD).sum(axis=1).max()) if dots.size else 1
    live_c = int((cloud != PAD).sum(axis=1).max()) if cloud.size else 1
    wl, wc = bucket(max(live_l, 1), 4), bucket(max(live_c, 1), 4)
    return DocBatch(
        jnp.asarray(dots[:, :wl]),
        jnp.asarray(np.asarray(batch.pay)[:, :wl]),
        batch.vv,
        jnp.asarray(cloud[:, :wc]),
    )
