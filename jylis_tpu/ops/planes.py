"""Two-plane u32 emulation of u64 counter tensors.

TPUs have no native 64-bit integer datapath: XLA emulates u64, and the
emulation is catastrophic exactly on the ops this framework is hottest on
(measured on v5e via the tunnel, (1M,64) tensors: u64 scatter 149 ms vs
u32 scatter 34 ms; u64 row-sum reduce 829 ms). So the counter keyspaces
store ``hi``/``lo`` u32 planes and do every heavy op in u32:

* **join (per-entry u64 max):** joint lexicographic compare of (hi, lo) —
  a handful of u32 compare/selects.
* **converge (scatter-merge):** gather current planes at the batch rows,
  join on the batch, scatter-SET both planes back with
  ``unique_indices=True``. A u64 scatter-max never happens. Requires
  unique rows per batch — which the serving repos guarantee (per-key
  pending dicts coalesce first); `coalesce` is the host-side helper for
  any caller that can't.
* **read (row sums):** each u32 plane splits into u16 halves summed in
  u32 (exact for up to 2^16 replica columns), recombined into u64 only on
  the tiny (K,) result.

All functions are pure and jittable.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

U32 = jnp.uint32
U64 = jnp.uint64


# ---- host-side helpers -----------------------------------------------------


def split64_np(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """u64 ndarray -> (hi, lo) u32 ndarrays."""
    x = np.asarray(x, dtype=np.uint64)
    return (x >> np.uint64(32)).astype(np.uint32), x.astype(np.uint32)


def combine64_np(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    return (np.asarray(hi, np.uint64) << np.uint64(32)) | np.asarray(lo, np.uint64)


def coalesce(key_idx: np.ndarray, deltas: np.ndarray):
    """Max-combine duplicate rows of a (B,) x (B, R) u64 delta batch on the
    host, returning unique rows + combined deltas (what converge requires)."""
    key_idx = np.asarray(key_idx)
    uniq, inv = np.unique(key_idx, return_inverse=True)
    out = np.zeros((len(uniq),) + deltas.shape[1:], np.uint64)
    np.maximum.at(out, inv, np.asarray(deltas, np.uint64))
    return uniq.astype(key_idx.dtype), out


# ---- device-side primitives ------------------------------------------------


def join_max(a_hi, a_lo, b_hi, b_lo):
    """Elementwise u64 max over plane pairs (joint lexicographic compare)."""
    take_b = (b_hi > a_hi) | ((b_hi == a_hi) & (b_lo > a_lo))
    return jnp.where(take_b, b_hi, a_hi), jnp.where(take_b, b_lo, a_lo)


def add_carry(a_hi, a_lo, b_hi, b_lo):
    """Elementwise u64 add with wraparound (Pony U64 overflow posture)."""
    lo = a_lo + b_lo
    carry = (lo < b_lo).astype(U32)
    return a_hi + b_hi + carry, lo


def scatter_join(hi, lo, key_idx, d_hi, d_lo):
    """Join a delta batch into (K, ...) planes at UNIQUE rows: gather ->
    joint max -> two u32 scatter-sets (mode="drop" for pad rows)."""
    cur_hi = hi[key_idx]
    cur_lo = lo[key_idx]
    new_hi, new_lo = join_max(cur_hi, cur_lo, d_hi, d_lo)
    return (
        hi.at[key_idx].set(new_hi, mode="drop", unique_indices=True),
        lo.at[key_idx].set(new_lo, mode="drop", unique_indices=True),
    )


def rowsum64(hi, lo) -> jnp.ndarray:
    """Sum of u64 values along the last axis, without u64 reductions:
    u16-split each plane, sum in u32, recombine on the small result.
    Exact for up to 2^16 summands (replica columns)."""
    mask = jnp.uint32(0xFFFF)

    def _split_sum(x):
        lo16 = jnp.sum(x & mask, axis=-1, dtype=U32).astype(U64)
        hi16 = jnp.sum(x >> jnp.uint32(16), axis=-1, dtype=U32).astype(U64)
        return lo16 + (hi16 << jnp.uint64(16))

    return _split_sum(lo) + (_split_sum(hi) << jnp.uint64(32))
