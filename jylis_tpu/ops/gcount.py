"""GCOUNT: grow-only counter lattice as batched TPU kernels.

Semantics (docs/_docs/types/gcount.md:43-47): state is a map
replica-id -> u64; join takes the per-replica max; the counter's value is the
sum over replicas. Driven by the reference repo at
jylis/repo_gcount.pony:25-60 (INC adds to this node's entry, GET sums).

TPU-native layout: the whole keyspace for the type is ONE dense tensor
``counts[key, replica] : uint64`` (replica ids are interned to columns on the
host). The per-key sequential converge loop of the reference
(repo_manager.pony:92-93) becomes a single scatter-max over the batch — one
XLA op regardless of batch size, which is the BASELINE.json north star.

All functions are pure and jittable; duplicate keys inside one batch are safe
because max/add are commutative-associative combiners.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

UINT64 = jnp.uint64


class GCountState(NamedTuple):
    """Dense grow-only counter keyspace: ``counts[key, replica]``."""

    counts: jax.Array  # (K, R) uint64


def init(num_keys: int, num_replicas: int) -> GCountState:
    return GCountState(jnp.zeros((num_keys, num_replicas), UINT64))


def join(a: GCountState, b: GCountState) -> GCountState:
    """Full-state lattice join: elementwise per-replica max."""
    return GCountState(jnp.maximum(a.counts, b.counts))


def converge_batch(
    state: GCountState, key_idx: jax.Array, deltas: jax.Array
) -> GCountState:
    """Join a batch of per-key deltas into the keyspace in one scatter-max.

    key_idx: (B,) int32 rows to merge into; deltas: (B, R) uint64 joinable
    delta states (absolute per-replica values, delta-CRDT style). Out-of-range
    rows are dropped, matching fire-and-forget delivery (SURVEY.md section 2.5).
    """
    return GCountState(state.counts.at[key_idx].max(deltas, mode="drop"))


def increment(
    state: GCountState,
    key_idx: jax.Array,
    replica_idx: jax.Array,
    amount: jax.Array,
) -> GCountState:
    """Local INC: add amounts at (key, replica) coordinates (u64 wraparound,
    same overflow posture as the reference's Pony u64)."""
    return GCountState(state.counts.at[key_idx, replica_idx].add(amount, mode="drop"))


def read(state: GCountState, key_idx: jax.Array) -> jax.Array:
    """GET for a batch of keys: row sums, uint64."""
    return jnp.sum(state.counts[key_idx], axis=-1, dtype=UINT64)


def read_all(state: GCountState) -> jax.Array:
    return jnp.sum(state.counts, axis=-1, dtype=UINT64)


def grow(state: GCountState, num_keys: int, num_replicas: int) -> GCountState:
    """Host-side capacity growth (zeros are the lattice identity)."""
    k, r = state.counts.shape
    if num_keys == k and num_replicas == r:
        return state
    out = jnp.zeros((num_keys, num_replicas), UINT64)
    return GCountState(out.at[:k, :r].set(state.counts))
