"""GCOUNT: grow-only counter lattice as batched TPU kernels.

Semantics (docs/_docs/types/gcount.md:43-47): state is a map
replica-id -> u64; join takes the per-replica max; the counter's value is the
sum over replicas. Driven by the reference repo at
jylis/repo_gcount.pony:25-60 (INC adds to this node's entry, GET sums).

TPU-native layout: the whole keyspace for the type is ONE dense tensor
``counts[key, replica]`` stored as hi/lo u32 planes (ops/planes.py — XLA's
u64 emulation is 4-25x slower on exactly the scatter/reduce ops this path
lives on). The per-key sequential converge loop of the reference
(repo_manager.pony:92-93) becomes a single gather -> joint-max -> scatter
composite over the batch — one fused XLA launch regardless of batch size,
which is the BASELINE.json north star.

Batches must carry UNIQUE key rows (the serving repos' pending dicts
guarantee it; `planes.coalesce` is the host helper otherwise).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import planes

U32 = jnp.uint32
U64 = jnp.uint64


class GCountState(NamedTuple):
    """Dense grow-only counter keyspace: u64 ``counts[key, replica]`` as
    two u32 planes."""

    hi: jax.Array  # (K, R) uint32
    lo: jax.Array  # (K, R) uint32


def init(num_keys: int, num_replicas: int) -> GCountState:
    # distinct buffers: the drain path donates the state, and XLA rejects
    # donating one aliased buffer twice
    return GCountState(
        jnp.zeros((num_keys, num_replicas), U32),
        jnp.zeros((num_keys, num_replicas), U32),
    )


def from_counts(counts) -> GCountState:
    """Build from a u64 ndarray (tests / interop)."""
    hi, lo = planes.split64_np(np.asarray(counts))
    return GCountState(jnp.asarray(hi), jnp.asarray(lo))


def to_counts(state: GCountState) -> np.ndarray:
    return planes.combine64_np(np.asarray(state.hi), np.asarray(state.lo))


def join(a: GCountState, b: GCountState) -> GCountState:
    """Full-state lattice join: elementwise per-replica u64 max."""
    return GCountState(*planes.join_max(a.hi, a.lo, b.hi, b.lo))


def converge_batch(
    state: GCountState, key_idx: jax.Array, d_hi: jax.Array, d_lo: jax.Array
) -> GCountState:
    """Join a batch of per-key deltas in one fused composite.

    key_idx: (B,) int32 UNIQUE rows; d_hi/d_lo: (B, R) u32 delta planes
    (absolute per-replica values, delta-CRDT style). Out-of-range rows are
    dropped, matching fire-and-forget delivery (SURVEY.md section 2.5).
    """
    return GCountState(*planes.scatter_join(state.hi, state.lo, key_idx, d_hi, d_lo))


def increment(
    state: GCountState,
    key_idx: jax.Array,
    replica_idx: jax.Array,
    amount: jax.Array,
) -> GCountState:
    """Local INC at UNIQUE (key, replica) coordinates: carry-propagating
    u64 add with wraparound (the reference's Pony u64 overflow posture).
    amount: (B,) uint64 (small host batches — split on device is cheap)."""
    a_hi = (amount >> jnp.uint64(32)).astype(U32)
    a_lo = amount.astype(U32)
    cur_hi = state.hi[key_idx, replica_idx]
    cur_lo = state.lo[key_idx, replica_idx]
    new_hi, new_lo = planes.add_carry(cur_hi, cur_lo, a_hi, a_lo)
    return GCountState(
        state.hi.at[key_idx, replica_idx].set(new_hi, mode="drop", unique_indices=True),
        state.lo.at[key_idx, replica_idx].set(new_lo, mode="drop", unique_indices=True),
    )


def read(state: GCountState, key_idx: jax.Array) -> jax.Array:
    """GET for a batch of keys: row sums, u64 with wraparound."""
    return planes.rowsum64(state.hi[key_idx], state.lo[key_idx])


def read_all(state: GCountState) -> jax.Array:
    return planes.rowsum64(state.hi, state.lo)


def grow(state: GCountState, num_keys: int, num_replicas: int) -> GCountState:
    """Host-side capacity growth (zeros are the lattice identity)."""
    k, r = state.hi.shape
    if num_keys == k and num_replicas == r:
        return state
    z = jnp.zeros((num_keys, num_replicas), U32)
    return GCountState(
        z.at[:k, :r].set(state.hi), z.at[:k, :r].set(state.lo)
    )
