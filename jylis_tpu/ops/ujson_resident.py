"""Device-resident UJSON keyspace: hot documents live ON the TPU.

Round-3 shape (superseded): every drain re-encoded each hot key's pending
deltas host->device, folded them on device, pulled the folded delta back
and host-converged it into the authoritative host doc — O(new deltas)
encode per drain, but also a device->host pull and a host O(doc) converge
per drain, and the 32-replica bench additionally re-encoded the replica
documents themselves every round (bench.py admitted the encode dominated).

This module keeps the hot keys' packed rows (ops/ujson_device.DocBatch:
sorted packed-dot planes + payload ids + vv + cloud) RESIDENT on the
device between drains. A drain then:

  1. encodes ONLY the new deltas into a (K, D, W) grid — O(new deltas),
  2. folds each key's D deltas and joins the result into that key's
     resident row in ONE fused dispatch (`fold_join_subset` /
     `fold_join_aligned`), entirely on device,
  3. decodes NOTHING — reads decode lazily (and cache host-side).

The reference's converge loop (repo_ujson.pony:96-110) walks the full
document once per delta; here the full document is never re-touched by
the host at all — steady-state host cost per drain is the delta encode.

Two properties keep a STREAM of drains fast on real hardware (measured
on the tunneled v5e: a recompile costs ~25s, a device round trip ~100ms):

* **No syncs, stable shapes.** A join's natural output width is the sum
  of its input widths, which would change the jitted shape EVERY drain.
  Instead the store tracks a host-side UPPER BOUND on the live row
  widths (admission widths + per-drain delta entry counts — removals
  only loosen the bound, never break it), and the fold kernels slice
  their output to the bucketed bound INSIDE the dispatch. Pads sort to
  the row tails, so slicing at >= the live width is lossless. Widths
  (and compiled shapes) then only change when the bound crosses a power
  of two, and no drain ever reads anything back from the device. Reads
  re-tighten the bound for free when they pull rows anyway.

* **Device causal-context compaction.** Host contexts absorb each
  contiguous dot into the version vector (ujson_host.CausalContext.
  compact); the round-3 device joins never did, so a resident row's
  cloud would grow by every dot ever seen. The fold kernels run a fused
  compaction epilogue (`_compact_ctx_row`): per replica column, the
  contiguous run of cloud dots above vv[col] absorbs into vv (a
  segmented-scan rank test on the sorted cloud row), and covered dots
  drop. Coverage (vv union cloud membership) is exactly preserved, so
  join semantics are untouched — it is the host compact, tensorised.

Layout migrations mirror the encode-side policy (ujson_device.plan_shift):
rows start in the narrow int32 dot layout and migrate IN PLACE on device
to the u64/32 layout the first time a seq or replica-column overflows the
narrow packing (`widen_rows`), or to a smaller narrow shift on replica
growth when every seq still fits (`repack_narrow` — provably safe because
a context covers its dot store, so the store's running max over delta
vv/cloud seqs bounds every seq on device). Seqs past u32 exceed every
device layout; `fold_in` raises OverflowError and the serving repo
demotes those keys to the host lattice.

Sharding: with a serving mesh, the row axis shards across devices and the
drain uses the row-ALIGNED fold (no gathers/scatters -> zero collectives,
SPMD like every plane-backed type); single-device serving uses the subset
fold (gather rows, join, scatter back) so a drain touching few of many
resident keys does not pay a full-batch join. Row 0 is a permanent
identity scratch row: subset-fold padding points spare slots at it, so
padded scatters write identical bytes and stay deterministic.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.batching import bucket
from . import ujson_device as dev
from .ujson_device import DocBatch, _join_inside, _pad_of

U32 = jnp.uint32
I32 = jnp.int32


# ---- fused device kernels --------------------------------------------------


def _fold_grid(grid: DocBatch, shift: int) -> DocBatch:
    """(K, D, W) grid -> (K, W') one folded row per key (the segmented
    fold, inlined into the callers' fused dispatches)."""
    return dev.fold_segments(grid, shift=shift)


def _compact_ctx_row(vv, cloud, shift: int):
    """The host CausalContext.compact, tensorised for one row: drop cloud
    dots covered by vv, absorb each column's contiguous run above vv[col]
    into vv. The cloud row is sorted and duplicate-free (joins dedup), so
    within a column's segment the kept seqs are strictly increasing —
    a dot absorbs iff seq == vv[col] + (its rank among kept) + 1, and a
    single pass is complete (any gap blocks everything after it)."""
    dt = cloud.dtype
    pad = _pad_of(dt)
    c = cloud.shape[-1]
    valid = cloud != pad
    col = jnp.minimum((cloud >> dt.type(shift)).astype(I32), vv.shape[-1] - 1)
    seq = (cloud & dt.type((1 << shift) - 1)).astype(U32)
    vvc = vv[col]
    drop = valid & (seq <= vvc)
    keep = valid & ~drop
    idx = jnp.arange(c, dtype=I32)
    prev_col = jnp.concatenate([jnp.full((1,), -1, I32), col[:-1]])
    is_new = valid & (col != prev_col)
    seg_start = jnp.maximum(
        jax.lax.cummax(jnp.where(is_new, idx, I32(-1))), 0
    )
    kept_before = jnp.concatenate(
        [jnp.zeros((1,), I32), jnp.cumsum(keep.astype(I32))[:-1]]
    )
    rank = kept_before - kept_before[seg_start]
    absorb = keep & (seq == vvc + rank.astype(U32) + 1)
    new_vv = vv.at[col].add(jnp.where(absorb, U32(1), U32(0)))
    new_cloud = jnp.sort(jnp.where(absorb | drop, pad, cloud))
    return new_vv, new_cloud


def _fit(plane, width: int, fill):
    """Slice or pad a (K, W) plane to the target width. Slicing is
    lossless whenever width covers the live row sizes (pads at tails)."""
    w = plane.shape[-1]
    if width == w:
        return plane
    if width < w:
        return plane[:, :width]
    k = plane.shape[0]
    return jnp.concatenate(
        [plane, jnp.full((k, width - w), fill, plane.dtype)], axis=-1
    )


def _finish(joined: DocBatch, shift: int, out_w: int, out_c: int) -> DocBatch:
    """Fold epilogue: compact contexts, then fit planes to the stable
    bucketed widths (all inside the same dispatch)."""
    vv, cloud = jax.vmap(partial(_compact_ctx_row, shift=shift))(
        joined.vv, joined.cloud
    )
    pad = _pad_of(joined.dots.dtype)
    return DocBatch(
        _fit(joined.dots, out_w, pad),
        _fit(joined.pay, out_w, -1),
        vv,
        _fit(cloud, out_c, pad),
    )


@partial(jax.jit, static_argnames=("shift", "out_w", "out_c"))
def fold_join_subset(
    resident: DocBatch, grid: DocBatch, idx, shift: int, out_w: int, out_c: int
) -> DocBatch:
    """Fold each grid segment and join into resident rows idx, one
    dispatch. idx rows must be unique EXCEPT for padded slots pointing at
    scratch row 0 with identity segments: identity joins are no-ops, so
    duplicate scatters to row 0 all write the same bytes (deterministic).
    Output planes are fit to (out_w, out_c) — the caller's width bound —
    so shapes stay stable across a stream of drains."""
    folded = _fold_grid(grid, shift)
    sub = DocBatch(*(p[idx] for p in resident))
    joined = _finish(_join_inside(sub, folded, shift), shift, out_w, out_c)
    pad = _pad_of(resident.dots.dtype)
    base = DocBatch(
        _fit(resident.dots, out_w, pad),
        _fit(resident.pay, out_w, -1),
        resident.vv,
        _fit(resident.cloud, out_c, pad),
    )
    return DocBatch(*(b.at[idx].set(j) for b, j in zip(base, joined)))


@partial(jax.jit, static_argnames=("shift", "out_w", "out_c"))
def fold_join_aligned(
    resident: DocBatch, grid: DocBatch, shift: int, out_w: int, out_c: int
) -> DocBatch:
    """Row-aligned variant: grid row i folds into resident row i. No
    gathers or scatters, so with both operands row-sharded over a mesh the
    whole drain is SPMD with zero collectives."""
    folded = _fold_grid(grid, shift)
    return _finish(_join_inside(resident, folded, shift), shift, out_w, out_c)


@partial(jax.jit, static_argnames=("shift", "out_w", "out_c"))
def fold_broadcast_rows(
    resident: DocBatch, deltas: DocBatch, shift: int, out_w: int, out_c: int
) -> DocBatch:
    """Fold a (D, W) delta batch to ONE doc and join it into EVERY
    resident row — the N-replica anti-entropy fan-in with the replica
    documents already resident (bench config 5 drives this)."""
    folded = dev._fold_body(deltas, shift)
    b = resident.dots.shape[0]
    tiled = DocBatch(
        *(jnp.broadcast_to(p, (b,) + p.shape[1:]) for p in folded)
    )
    return _finish(_join_inside(resident, tiled, shift), shift, out_w, out_c)


@partial(jax.jit, static_argnames=("w", "c"))
def slice_widths(batch: DocBatch, w: int, c: int) -> DocBatch:
    """Re-bucket plane widths to (w, c) — safe whenever w/c cover the
    live widths, because joined rows keep pads sorted to the tail."""
    pad = _pad_of(batch.dots.dtype)
    return DocBatch(
        _fit(batch.dots, w, pad),
        _fit(batch.pay, w, -1),
        batch.vv,
        _fit(batch.cloud, c, pad),
    )


@jax.jit
def live_widths(batch: DocBatch):
    """(2,) int32: max live dot / cloud width over rows (pads at tails).
    Read at would-widen moments to re-tighten the host width bounds —
    redelivered deltas inflate the bounds but not the live state, and
    this one small pull is what keeps them from forcing spurious plane
    growth (and recompiles)."""
    pad = _pad_of(batch.dots.dtype)
    ld = jnp.max(jnp.sum((batch.dots != pad).astype(I32), axis=-1))
    lc = jnp.max(jnp.sum((batch.cloud != pad).astype(I32), axis=-1))
    return jnp.stack([ld, lc])


@jax.jit
def remap_pay(batch: DocBatch, table) -> DocBatch:
    """Rewrite payload ids through a compaction table (-1 stays -1)."""
    pay = jnp.where(batch.pay >= 0, table[jnp.maximum(batch.pay, 0)], -1)
    return DocBatch(batch.dots, pay, batch.vv, batch.cloud)


@partial(jax.jit, static_argnames=("old_shift",))
def widen_rows(batch: DocBatch, old_shift: int) -> DocBatch:
    """Migrate narrow int32 rows to the u64/32 layout in place on device.

    (col << old_shift | seq) -> (col << 32 | seq) is monotone in (col,
    seq), so row sort order survives; narrow pads map to the u64 pad."""
    mask = (1 << old_shift) - 1

    def w(plane):
        p64 = plane.astype(jnp.uint64)
        repacked = ((p64 >> old_shift) << jnp.uint64(32)) | (
            p64 & jnp.uint64(mask)
        )
        return jnp.where(plane == dev.PAD32, dev.PAD64, repacked)

    return DocBatch(w(batch.dots), batch.pay, batch.vv, w(batch.cloud))


@partial(jax.jit, static_argnames=("old_shift", "new_shift"))
def repack_narrow(batch: DocBatch, old_shift: int, new_shift: int) -> DocBatch:
    """Re-pack int32 rows at a smaller shift (replica-column growth that
    still fits a narrow layout). The caller must have verified every seq
    ever encoded is < 2**new_shift - 1 (strictly: the all-ones seq at the
    top column would collide with the pad). The map is monotone in
    (col, seq), so sorted rows stay sorted."""
    mask = (1 << old_shift) - 1

    def w(plane):
        repacked = ((plane >> old_shift) << new_shift) | (plane & mask)
        return jnp.where(plane == dev.PAD32, dev.PAD32, repacked)

    return DocBatch(w(batch.dots), batch.pay, batch.vv, w(batch.cloud))


@jax.jit
def clear_rows(batch: DocBatch, mask) -> DocBatch:
    """Reset masked rows to the identity document (eviction)."""
    pad = _pad_of(batch.dots.dtype)
    m = mask[:, None]
    return DocBatch(
        jnp.where(m, pad, batch.dots),
        jnp.where(m, -1, batch.pay),
        jnp.where(m, U32(0), batch.vv),
        jnp.where(m, pad, batch.cloud),
    )


@jax.jit
def place_rows(batch: DocBatch, rows: DocBatch, idx) -> DocBatch:
    """Write freshly-encoded rows into free slots (admission). Plane
    widths must already be harmonised by the caller."""
    return DocBatch(
        batch.dots.at[idx].set(rows.dots),
        batch.pay.at[idx].set(rows.pay),
        batch.vv.at[idx].set(rows.vv),
        batch.cloud.at[idx].set(rows.cloud),
    )


@partial(jax.jit, static_argnames=("rows",))
def grow_capacity(batch: DocBatch, rows: int) -> DocBatch:
    """Append identity rows (capacity growth, bucketed by the caller)."""
    pad = _pad_of(batch.dots.dtype)
    k = batch.dots.shape[0]

    def app(plane, fill):
        return jnp.concatenate(
            [plane, jnp.full((rows - k,) + plane.shape[1:], fill, plane.dtype)],
            axis=0,
        )

    return DocBatch(
        app(batch.dots, pad), app(batch.pay, -1), app(batch.vv, 0),
        app(batch.cloud, pad),
    )


@partial(jax.jit, static_argnames=("n_rep",))
def grow_reps(batch: DocBatch, n_rep: int) -> DocBatch:
    """Widen the vv plane for replica-column growth (interner append-only,
    so existing columns keep their meaning)."""
    k, r = batch.vv.shape
    vv = jnp.concatenate(
        [batch.vv, jnp.zeros((k, n_rep - r), U32)], axis=-1
    )
    return DocBatch(batch.dots, batch.pay, vv, batch.cloud)


# ---- the store -------------------------------------------------------------


class ResidentStore:
    """Hot UJSON keys as device-resident DocBatch rows.

    Host-side bookkeeping: key->row map, free rows, the replica-id and
    payload interners (shared across every row, append-only), the current
    dot layout (shift), and the width upper bounds the fold kernels slice
    to. All device mutations go through the jitted kernels above.
    """

    ROW_BUCKET = 8  # capacity granularity (rows)
    # soft HBM budget for the resident planes: admission stops (keys fall
    # back to the host lattice) once the projected plane bytes cross it.
    # Width growth on already-resident keys is data the host would hold
    # in RAM anyway; admission count is the axis that must not run away
    BYTE_BUDGET = 256 << 20

    def __init__(self, n_rep: int = 8, mesh=None, shard_fn=None):
        self._mesh = mesh
        self._shard_fn = shard_fn  # parallel.shard_docbatch, mesh-bound
        self._nrep = bucket(n_rep, 4)
        self._shift = dev.narrow_shift(self._nrep)
        self._rid_cols: dict[int, int] = {}
        self._pay_ids: dict[tuple, int] = {}
        self._pay_rev: list[tuple] = []
        self._rows: dict[bytes, int] = {}
        self._free: list[int] = []
        self._batch: DocBatch | None = None
        # host-side width upper bounds (see module docstring): grow by
        # admission widths and per-drain delta counts, tighten for free
        # whenever a full read pulls the planes anyway
        self._ub_w = 1
        self._ub_c = 1
        # the largest seq ever encoded into the store: a causal context
        # covers its dot store, so the running max over delta vv/cloud
        # seqs bounds every seq on device — which is what makes the
        # narrow->narrow repack on replica growth provably safe
        self._max_seq = 0

    # -- interners ----------------------------------------------------------

    def pay(self, path, token) -> int:
        k = (path, token)
        pid = self._pay_ids.get(k)
        if pid is None:
            pid = self._pay_ids[k] = len(self._pay_rev)
            self._pay_rev.append(k)
        return pid

    def pay_lookup(self, pid: int):
        return self._pay_rev[pid]

    # -- introspection ------------------------------------------------------

    def __contains__(self, key: bytes) -> bool:
        return key in self._rows

    def __len__(self) -> int:
        return len(self._rows)

    def keys(self):
        return self._rows.keys()

    def block(self) -> None:
        """Wait for every queued device mutation (timing/shutdown)."""
        if self._batch is not None:
            jax.block_until_ready(self._batch.dots)

    def approx_bytes(self) -> int:
        """Projected resident plane footprint (current shapes)."""
        if self._batch is None:
            return 0
        return sum(p.size * p.dtype.itemsize for p in self._batch)

    def full(self) -> bool:
        """True when admission should stop (BYTE_BUDGET crossed): the
        serving repo keeps further keys on the host lattice."""
        return self.approx_bytes() >= self.BYTE_BUDGET

    # -- layout plumbing ----------------------------------------------------

    def _row_axis(self) -> int:
        return self._batch.dots.shape[0] if self._batch is not None else 0

    def _capacity_for(self, rows: int) -> int:
        cap = bucket(max(rows, 2), self.ROW_BUCKET)
        if self._mesh is not None:
            m = self._mesh.devices.size
            cap += -cap % m
        return cap

    def _shard(self, batch: DocBatch) -> DocBatch:
        if self._shard_fn is None:
            return batch
        return self._shard_fn(batch)

    def _out_widths(self) -> tuple[int, int]:
        return bucket(self._ub_w, 4), bucket(self._ub_c, 4)

    def _budget_widths(self, grow_w: int, grow_c: int) -> tuple[int, int]:
        """Width targets for the next fold. If the (upper-bound) growth
        would WIDEN the planes, first re-tighten the bounds from the
        device (one small pull): redelivered deltas inflate the bound
        while the join dedups them, and without this check every
        redelivery storm would grow the planes — and recompile the fold
        (~25s) — for no live data. After tightening, genuine growth
        still widens (and compiles) as it must."""
        self._ub_w += grow_w
        self._ub_c += grow_c
        out_w, out_c = self._out_widths()
        if self._batch is not None and (
            out_w > self._batch.dots.shape[-1]
            or out_c > self._batch.cloud.shape[-1]
        ):
            ld, lc = (int(x) for x in jax.device_get(live_widths(self._batch)))
            self._ub_w = max(ld, 1) + grow_w
            self._ub_c = max(lc, 1) + grow_c
            out_w, out_c = self._out_widths()
        return out_w, out_c

    def _note_seqs(self, docs) -> None:
        """Track the max seq across delta contexts (context covers store,
        so vv+cloud bound the entries too)."""
        m = self._max_seq
        for d in docs:
            for s in d.ctx.vv.values():
                if s > m:
                    m = s
            for _, s in d.ctx.cloud:
                if s > m:
                    m = s
        self._max_seq = m

    def _widen(self) -> None:
        if self._shift == 32:
            return
        if self._batch is not None:
            self._batch = self._shard(widen_rows(self._batch, self._shift))
        self._shift = 32

    def _ensure_reps(self) -> None:
        """After any encode grew the rid interner: widen vv columns, and
        re-pack the dot layout if the replica-column budget no longer
        fits — to a smaller narrow shift when every seq ever encoded
        still fits it, else to u64/32."""
        n = len(self._rid_cols)
        if self._shift != 32 and n > (1 << (31 - self._shift)):
            s2 = dev.narrow_shift(bucket(n, 4))
            if self._max_seq < (1 << s2) - 1:
                if self._batch is not None:
                    self._batch = self._shard(
                        repack_narrow(self._batch, self._shift, s2)
                    )
                self._shift = s2
            else:
                self._widen()
        if n > self._nrep:
            self._nrep = bucket(n, 4)
            if self._batch is not None:
                self._batch = self._shard(grow_reps(self._batch, self._nrep))

    def _encode_rows(self, docs) -> DocBatch:
        """Encode host docs at the store's current layout, migrating the
        store when the narrow layout can't hold them. OverflowError
        escapes only when even u64/32 can't (seq past u32)."""
        while True:
            try:
                b = dev._encode_docs_np(
                    docs, self._rid_cols, self.pay, self._nrep, shift=self._shift
                )
            except OverflowError:
                if self._shift == 32:
                    raise
                self._widen()
                continue
            except ValueError:  # rid interner outgrew the vv budget
                self._ensure_reps()
                continue
            # a successful encode at self._nrep proves the interner fits
            # it (the encoder checks); _ensure_reps only handles the
            # narrow-shift budget here
            self._ensure_reps()
            return b

    def _encode_grid(self, groups) -> DocBatch:
        while True:
            try:
                g = dev.encode_doc_groups(
                    groups, self._rid_cols, self.pay, self._nrep,
                    shift=self._shift,
                )
            except OverflowError:
                if self._shift == 32:
                    raise
                self._widen()
                continue
            except ValueError:
                self._ensure_reps()
                continue
            self._ensure_reps()
            return g

    # -- admission / eviction ------------------------------------------------

    def admit(self, items: list[tuple[bytes, object]]) -> None:
        """Make keys resident with their current host docs (encoded ONCE;
        after this only reads ever decode them again)."""
        items = [(k, d) for k, d in items if k not in self._rows]
        if not items:
            return
        self._note_seqs([d for _, d in items])
        # entries are not covered by _note_seqs' vv/cloud shortcut for
        # admitted FULL docs only in theory; the ORSWOT invariant (ctx
        # covers store) holds for every doc the host lattice builds, so
        # vv alone still bounds them
        rows_np = self._encode_rows([d for _, d in items])
        self._ub_w = max(self._ub_w, rows_np.dots.shape[-1])
        self._ub_c = max(self._ub_c, rows_np.cloud.shape[-1])
        if self._batch is None:
            cap = self._capacity_for(len(items) + 1)
            pad = _pad_of(np.int32 if self._shift < 32 else np.uint64)
            dtype = np.int32 if self._shift < 32 else np.uint64
            w = rows_np.dots.shape[-1]
            c = rows_np.cloud.shape[-1]
            self._batch = self._shard(
                DocBatch(
                    jnp.asarray(np.full((cap, w), pad, dtype)),
                    jnp.asarray(np.full((cap, w), -1, np.int32)),
                    jnp.asarray(np.zeros((cap, self._nrep), np.uint32)),
                    jnp.asarray(np.full((cap, c), pad, dtype)),
                )
            )
            self._free = list(range(cap - 1, 0, -1))  # row 0 is scratch
        need = len(items)
        if len(self._free) < need:
            old = self._row_axis()
            cap = self._capacity_for(old + need - len(self._free))
            self._batch = self._shard(grow_capacity(self._batch, cap))
            self._free = list(range(cap - 1, old - 1, -1)) + self._free
        # harmonise widths between the resident planes and the new rows
        bw, bc = self._batch.dots.shape[-1], self._batch.cloud.shape[-1]
        rw, rc = rows_np.dots.shape[-1], rows_np.cloud.shape[-1]
        if rw > bw or rc > bc:
            self._batch = self._shard(
                slice_widths(self._batch, max(rw, bw), max(rc, bc))
            )
            bw, bc = max(rw, bw), max(rc, bc)
        if rw < bw or rc < bc:
            rows_np = _pad_planes_np(rows_np, bw, bc)
        idx = np.empty(need, np.int32)
        for j, (key, _) in enumerate(items):
            row = self._free.pop()
            self._rows[key] = row
            idx[j] = row
        self._batch = self._shard(
            place_rows(self._batch, DocBatch(*(jnp.asarray(p) for p in rows_np)),
                       jnp.asarray(idx))
        )

    def evict(self, key: bytes):
        """Decode a key's current doc and drop its row (demotion to the
        host lattice, e.g. before a local write)."""
        doc = self.read(key)
        self.discard(key)
        return doc

    def discard(self, key: bytes) -> None:
        """Drop a key's row WITHOUT decoding (the caller already holds a
        current host view, e.g. the serving repo's read cache)."""
        row = self._rows.pop(key)
        mask = np.zeros(self._row_axis(), bool)
        mask[row] = True
        self._batch = self._shard(clear_rows(self._batch, jnp.asarray(mask)))
        self._free.append(row)

    # -- the drain ----------------------------------------------------------

    def fold_in(self, pending: dict[bytes, list]) -> None:
        """Fold each key's pending deltas into its resident row — ONE
        device dispatch for every key in the drain, no host read-backs.
        Raises OverflowError (rows unchanged) when a delta exceeds the
        u64/32 layout; the caller demotes those keys to the host
        lattice."""
        pending = {k: v for k, v in pending.items() if v and k in self._rows}
        if not pending:
            return
        self._note_seqs([d for lst in pending.values() for d in lst])
        # width bound: each row grows by at most its group's entry/cloud
        # counts (the join can only drop), so the batch max grows by at
        # most the largest group's counts
        grow_w = grow_c = 0
        for lst in pending.values():
            ew = sum(len(d.entries) for d in lst)
            ec = sum(len(d.ctx.cloud) for d in lst)
            if ew > grow_w:
                grow_w = ew
            if ec > grow_c:
                grow_c = ec
        if self._mesh is None and len(pending) <= len(self._rows) // 2:
            self._fold_subset(pending, grow_w, grow_c)
        else:
            self._fold_aligned(pending, grow_w, grow_c)

    def fold_in_broadcast(self, deltas: list) -> None:
        """Fold one delta list into EVERY resident row (the all-replicas
        anti-entropy shape). Same contracts as fold_in."""
        if not deltas or not self._rows:
            return
        from .ujson_host import UJSON

        self._note_seqs(deltas)
        d = bucket(len(deltas), 4)  # identity-pad: bound the jit cache
        batch = self._encode_rows(list(deltas) + [UJSON()] * (d - len(deltas)))
        out_w, out_c = self._budget_widths(
            sum(len(x.entries) for x in deltas),
            sum(len(x.ctx.cloud) for x in deltas),
        )
        # the delta batch's leading axis is deltas, not resident rows;
        # it stays replicated (only the resident planes are row-sharded)
        batch = DocBatch(*(jnp.asarray(p) for p in batch))
        self._batch = self._shard(
            fold_broadcast_rows(
                self._batch, batch, shift=self._shift, out_w=out_w, out_c=out_c
            )
        )

    def _fold_subset(self, pending, grow_w: int, grow_c: int) -> None:
        ks = sorted(pending)
        n = bucket(len(ks), 4)
        groups = [pending[k] for k in ks] + [[] for _ in range(n - len(ks))]
        grid = self._encode_grid(groups)
        out_w, out_c = self._budget_widths(grow_w, grow_c)
        idx = np.zeros(n, np.int32)  # pad slots -> scratch row 0
        for j, k in enumerate(ks):
            idx[j] = self._rows[k]
        grid = DocBatch(*(jnp.asarray(p) for p in grid))
        self._batch = fold_join_subset(
            self._batch, grid, jnp.asarray(idx), shift=self._shift,
            out_w=out_w, out_c=out_c,
        )

    def _fold_aligned(self, pending, grow_w: int, grow_c: int) -> None:
        cap = self._row_axis()
        groups: list[list] = [[] for _ in range(cap)]
        for k, lst in pending.items():
            groups[self._rows[k]] = lst
        grid = self._encode_grid(groups)
        out_w, out_c = self._budget_widths(grow_w, grow_c)
        grid = self._shard(DocBatch(*(jnp.asarray(p) for p in grid)))
        self._batch = self._shard(
            fold_join_aligned(
                self._batch, grid, shift=self._shift, out_w=out_w, out_c=out_c
            )
        )

    # -- reads ---------------------------------------------------------------

    def read(self, key: bytes):
        """Decode ONE key's doc (device->host pull of its row slices)."""
        return self.read_many([key])[0]

    def read_many(self, keys: list[bytes]) -> list:
        rows = jnp.asarray(
            np.array([self._rows[k] for k in keys], np.int32)
        )
        sub = DocBatch(*(p[rows] for p in self._batch))
        np_sub = DocBatch(*jax.device_get(tuple(sub)))  # one transfer
        if len(keys) == len(self._rows):
            # a full read pulled every row anyway: re-tighten the width
            # bounds (and re-bucket the planes) for free
            pad = _pad_of(np_sub.dots.dtype)
            self._ub_w = max(int((np_sub.dots != pad).sum(axis=1).max()), 1)
            self._ub_c = max(int((np_sub.cloud != pad).sum(axis=1).max()), 1)
            w, c = self._out_widths()
            if (
                w < self._batch.dots.shape[-1]
                or c < self._batch.cloud.shape[-1]
            ):
                self._batch = self._shard(slice_widths(self._batch, w, c))
        cols_rid = {c: r for r, c in self._rid_cols.items()}
        docs = dev.decode_batch(
            np_sub, cols_rid, self.pay_lookup, shift=self._shift
        )
        if len(keys) == len(self._rows):
            self._compact_pay(np_sub)
        return docs

    def _compact_pay(self, np_sub: DocBatch) -> None:
        """Payload-interner epoch compaction (the ops/interner.py hazard:
        append-only tables leak under value churn). Runs on full reads —
        the pulled pay planes ARE the live-id census — when dead ids
        dominate: rebuild the interner from the live ids and remap the
        device plane through a table in one dispatch."""
        live = np.unique(np_sub.pay)
        live = live[live >= 0]
        if len(self._pay_rev) <= 2 * max(len(live), 16):
            return
        table = np.full(len(self._pay_rev), -1, np.int32)
        new_rev = []
        for pid in live:
            table[pid] = len(new_rev)
            new_rev.append(self._pay_rev[pid])
        self._pay_rev = new_rev
        self._pay_ids = {k: i for i, k in enumerate(new_rev)}
        self._batch = self._shard(remap_pay(self._batch, jnp.asarray(table)))

    def dump(self) -> list[tuple[bytes, object]]:
        """Decode every resident key (snapshots / bootstrap sync)."""
        if not self._rows:
            return []
        keys = sorted(self._rows)
        return list(zip(keys, self.read_many(keys)))


def _pad_planes_np(batch: DocBatch, w: int, c: int) -> DocBatch:
    pad = _pad_of(batch.dots.dtype)
    k = batch.dots.shape[0]

    def padto(plane, width, fill):
        extra = width - plane.shape[-1]
        if extra <= 0:
            return plane
        return np.concatenate(
            [plane, np.full((k, extra), fill, plane.dtype)], axis=-1
        )

    return DocBatch(
        padto(batch.dots, w, pad), padto(batch.pay, w, -1), batch.vv,
        padto(batch.cloud, c, pad),
    )
