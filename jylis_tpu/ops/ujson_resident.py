"""Device-resident UJSON keyspace: hot documents live ON the TPU.

Round-3 shape (superseded): every drain re-encoded each hot key's pending
deltas host->device, folded them on device, pulled the folded delta back
and host-converged it into the authoritative host doc — O(new deltas)
encode per drain, but also a device->host pull and a host O(doc) converge
per drain, and the 32-replica bench additionally re-encoded the replica
documents themselves every round (bench.py admitted the encode dominated).

This module keeps the hot keys' packed rows (ops/ujson_device.DocBatch:
sorted packed-dot planes + payload ids + vv + cloud) RESIDENT on the
device between drains. A drain then:

  1. encodes ONLY the new deltas into a (K, D, W) grid — O(new deltas),
  2. folds each key's D deltas and joins the result into that key's
     resident row in ONE fused dispatch (`fold_join_subset` /
     `fold_join_aligned`), entirely on device,
  3. decodes NOTHING — reads decode lazily (and cache host-side).

The reference's converge loop (repo_ujson.pony:96-110) walks the full
document once per delta; here the full document is never re-touched by
the host at all — steady-state host cost per drain is the delta encode.

Two properties keep a STREAM of drains fast on real hardware (round-3
environment numbers from the tunneled v5e, stamped here as historical
context rather than derived from BENCH_full.json: a recompile costs
~25s, a device round trip ~100ms):

* **No syncs, stable shapes.** A join's natural output width is the sum
  of its input widths, which would change the jitted shape EVERY drain.
  Instead the store tracks a host-side UPPER BOUND on the live row
  widths (admission widths + per-drain delta entry counts — removals
  only loosen the bound, never break it), and the fold kernels slice
  their output to the bucketed bound INSIDE the dispatch. Pads sort to
  the row tails, so slicing at >= the live width is lossless. Widths
  (and compiled shapes) then only change when the bound crosses a power
  of two, and no drain ever reads anything back from the device. Reads
  re-tighten the bound for free when they pull rows anyway.

* **Device causal-context compaction.** Host contexts absorb each
  contiguous dot into the version vector (ujson_host.CausalContext.
  compact); the round-3 device joins never did, so a resident row's
  cloud would grow by every dot ever seen. The fold kernels run a fused
  compaction epilogue (`_compact_ctx_row`): per replica column, the
  contiguous run of cloud dots above vv[col] absorbs into vv (a
  segmented-scan rank test on the sorted cloud row), and covered dots
  drop. Coverage (vv union cloud membership) is exactly preserved, so
  join semantics are untouched — it is the host compact, tensorised.

Layout migrations mirror the encode-side policy (ujson_device.plan_shift):
rows start in the narrow int32 dot layout and migrate IN PLACE on device
to the u64/32 layout the first time a seq or replica-column overflows the
narrow packing (`widen_rows`), or to a smaller narrow shift on replica
growth when every seq still fits (`repack_narrow` — provably safe because
a context covers its dot store, so the store's running max over delta
vv/cloud seqs bounds every seq on device). Seqs past u32 exceed every
device layout; `fold_in` raises OverflowError and the serving repo
demotes those keys to the host lattice.

Sharding: with a serving mesh, the row axis shards across devices and the
drain uses the row-ALIGNED fold (no gathers/scatters -> zero collectives,
SPMD like every plane-backed type); single-device serving uses the subset
fold (gather rows, join, scatter back) so a drain touching few of many
resident keys does not pay a full-batch join. Row 0 is a permanent
identity scratch row: subset-fold padding points spare slots at it, so
padded scatters write identical bytes and stay deterministic.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.batching import bucket
from . import ujson_device as dev
from .ujson_device import DocBatch, _join_inside, _pad_of

U32 = jnp.uint32
I32 = jnp.int32


# ---- fused device kernels --------------------------------------------------


def _fold_flat_one(g: DocBatch, shift: int) -> DocBatch:
    """Fold ONE key's (D, W) delta stack to a single row in closed form.

    The pairwise fold tree (ops/ujson_device.fold_segments) widens its
    intermediates to D*W — mostly pads for small deltas — and pays a
    sort per level. But for a FOLD (not a general join) there is a flat
    rule: an entry survives iff every delta either CONTAINS it or does
    not COVER it (containment implies coverage, so the delta that minted
    it never votes against it; associativity of the ORSWOT join makes
    the n-way statement exact). That is one (D, E) membership/coverage
    probe matrix and a reduce — no tree, no intermediate widening, and
    the single output sort. Contexts fold as an elementwise vv max plus
    one cloud sort+dedup.
    """
    dt = g.dots.dtype
    pad = _pad_of(dt)
    d, w = g.dots.shape
    dots = g.dots.reshape(d * w)
    pay = g.pay.reshape(d * w)
    valid = dots != pad
    rid = jnp.minimum((dots >> dt.type(shift)).astype(I32), g.vv.shape[-1] - 1)
    seq = (dots & dt.type((1 << shift) - 1)).astype(U32)
    # (D, E): does delta j cover entry e? (vv lookup or cloud membership)
    cover = (seq[None, :] <= g.vv[:, rid]) | jax.vmap(
        lambda row: dev._member(row, dots)
    )(g.cloud)
    # (D, E): does delta j contain entry e? (rows are sorted)
    present = jax.vmap(lambda row: dev._member(row, dots))(g.dots)
    survive = valid & jnp.all(present | ~cover, axis=0)
    out_dots = jnp.where(survive, dots, pad)
    out_pay = jnp.where(survive, pay, -1)
    order = jnp.argsort(out_dots)
    out_dots = out_dots[order]
    out_pay = out_pay[order]
    # dedup equal dots (several deltas carrying the same entry): keep one
    dup = jnp.concatenate(
        [out_dots[:-1] == out_dots[1:], jnp.zeros((1,), bool)]
    )
    d2 = jnp.where(dup, pad, out_dots)
    p2 = jnp.where(dup, -1, out_pay)
    order2 = jnp.argsort(d2)
    vv = jnp.max(g.vv, axis=0)
    cl = jnp.sort(g.cloud.reshape(d * g.cloud.shape[-1]))
    cdup = jnp.concatenate([jnp.zeros((1,), bool), cl[1:] == cl[:-1]])
    cloud = jnp.sort(jnp.where(cdup, pad, cl))
    return DocBatch(d2[order2], p2[order2], vv, cloud)


def _fold_grid(grid: DocBatch, shift: int) -> DocBatch:
    """(K, D, W) grid -> one folded row per key, all keys in the same
    dispatch (inlined into the callers' fused kernels).

    Two shapes: the log-depth pairwise tree (ops/ujson_device) probes
    O(E log E) per key but widens intermediates with pads; the flat
    closed-form rule (_fold_flat_one) never widens but probes O(E*D).
    Probes are gather-bound on this hardware, so the tree wins for deep
    stacks and flat wins for shallow ones; measured crossover ~64."""
    if grid.dots.shape[1] <= 64:
        return jax.vmap(partial(_fold_flat_one, shift=shift))(grid)
    return dev.fold_segments(grid, shift=shift)


def _compact_ctx_row(vv, cloud, shift: int):
    """The host CausalContext.compact, tensorised for one row: drop cloud
    dots covered by vv, absorb each column's contiguous run above vv[col]
    into vv. The cloud row is sorted and duplicate-free (joins dedup), so
    within a column's segment the kept seqs are strictly increasing —
    a dot absorbs iff seq == vv[col] + (its rank among kept) + 1, and a
    single pass is complete (any gap blocks everything after it)."""
    dt = cloud.dtype
    pad = _pad_of(dt)
    r = vv.shape[-1]
    valid = cloud != pad
    col = jnp.minimum((cloud >> dt.type(shift)).astype(I32), r - 1)
    seq = (cloud & dt.type((1 << shift) - 1)).astype(U32)
    # computed-index gathers/scatters are pathologically slow on this
    # chip (BENCH r01 note); R is small and static, so per-column masks
    # do the vv lookup and the absorb counting as dense lane ops instead
    colmask = col[None, :] == jnp.arange(r, dtype=I32)[:, None]  # (R, C)
    vvc = jnp.sum(jnp.where(colmask, vv[:, None], U32(0)), axis=0, dtype=U32)
    drop = valid & (seq <= vvc)
    keep = valid & ~drop
    prev_col = jnp.concatenate([jnp.full((1,), -1, I32), col[:-1]])
    is_new = valid & (col != prev_col)
    kept_before = jnp.concatenate(
        [jnp.zeros((1,), I32), jnp.cumsum(keep.astype(I32))[:-1]]
    )
    # kept_before is non-decreasing, so the value at the latest segment
    # start is a running max over the marked positions (no gather)
    seg_base = jnp.maximum(
        jax.lax.cummax(jnp.where(is_new, kept_before, I32(-1))), 0
    )
    rank = kept_before - seg_base
    absorb = keep & (seq == vvc + rank.astype(U32) + 1)
    new_vv = vv + jnp.sum(
        (colmask & absorb[None, :]).astype(U32), axis=1, dtype=U32
    )
    new_cloud = jnp.sort(jnp.where(absorb | drop, pad, cloud))
    return new_vv, new_cloud


def _fit(plane, width: int, fill):
    """Slice or pad a (K, W) plane to the target width. Slicing is
    lossless whenever width covers the live row sizes (pads at tails)."""
    w = plane.shape[-1]
    if width == w:
        return plane
    if width < w:
        return plane[:, :width]
    k = plane.shape[0]
    return jnp.concatenate(
        [plane, jnp.full((k, width - w), fill, plane.dtype)], axis=-1
    )


def _finish(joined: DocBatch, shift: int, out_w: int, out_c: int) -> DocBatch:
    """Fold epilogue: compact contexts, then fit planes to the stable
    bucketed widths (all inside the same dispatch)."""
    vv, cloud = jax.vmap(partial(_compact_ctx_row, shift=shift))(
        joined.vv, joined.cloud
    )
    pad = _pad_of(joined.dots.dtype)
    return DocBatch(
        _fit(joined.dots, out_w, pad),
        _fit(joined.pay, out_w, -1),
        vv,
        _fit(cloud, out_c, pad),
    )


@partial(jax.jit, static_argnames=("shift", "out_w", "out_c"))
def fold_join_subset(
    resident: DocBatch, grid: DocBatch, idx, shift: int, out_w: int, out_c: int
) -> tuple[DocBatch, jax.Array]:
    """Fold each grid segment and join into resident rows idx, one
    dispatch. idx rows must be unique EXCEPT for padded slots pointing at
    scratch row 0 with identity segments: identity joins are no-ops, so
    duplicate scatters to row 0 all write the same bytes (deterministic).
    Output planes are fit to (out_w, out_c) — the caller's width bound —
    so shapes stay stable across a stream of drains."""
    folded = _fold_grid(grid, shift)
    sub = DocBatch(*(p[idx] for p in resident))
    joined = _finish(_join_inside(sub, folded, shift), shift, out_w, out_c)
    pad = _pad_of(resident.dots.dtype)
    base = DocBatch(
        _fit(resident.dots, out_w, pad),
        _fit(resident.pay, out_w, -1),
        resident.vv,
        _fit(resident.cloud, out_c, pad),
    )
    out = DocBatch(*(b.at[idx].set(j) for b, j in zip(base, joined)))
    # live widths of the FULL batch (untouched rows included): the
    # store's width bound must cover every row, not just the subset
    return out, live_widths(out)


@partial(jax.jit, static_argnames=("shift", "out_w", "out_c"))
def fold_join_aligned(
    resident: DocBatch, grid: DocBatch, shift: int, out_w: int, out_c: int
) -> tuple[DocBatch, jax.Array]:
    """Row-aligned variant: grid row i folds into resident row i. No
    gathers or scatters, so with both operands row-sharded over a mesh the
    whole drain is SPMD with zero collectives."""
    folded = _fold_grid(grid, shift)
    out = _finish(_join_inside(resident, folded, shift), shift, out_w, out_c)
    return out, live_widths(out)


@partial(jax.jit, static_argnames=("shift", "out_w", "out_c"))
def fold_broadcast_rows(
    resident: DocBatch,
    deltas: DocBatch,
    occupied,
    shift: int,
    out_w: int,
    out_c: int,
) -> tuple[DocBatch, jax.Array]:
    """Fold a (D, W) delta batch to ONE doc and join it into every
    OCCUPIED resident row — the N-replica anti-entropy fan-in with the
    replica documents already resident (bench config 5 drives this).
    Scratch row 0 and free rows re-clear in the same dispatch, so the
    row-0-is-identity invariant holds and the returned live widths
    measure occupied rows only (free-row garbage would inflate the
    store's width bound — ADVICE round 4)."""
    if deltas.dots.shape[0] <= 64:
        folded = _fold_flat_one(deltas, shift)
        folded = DocBatch(*(p[None] for p in folded))
    else:
        folded = dev._fold_body(deltas, shift)
    b = resident.dots.shape[0]
    tiled = DocBatch(
        *(jnp.broadcast_to(p, (b,) + p.shape[1:]) for p in folded)
    )
    out = _finish(_join_inside(resident, tiled, shift), shift, out_w, out_c)
    out = clear_rows(out, ~occupied)
    return out, live_widths(out)


@partial(jax.jit, static_argnames=("w", "c"))
def slice_widths(batch: DocBatch, w: int, c: int) -> DocBatch:
    """Re-bucket plane widths to (w, c) — safe whenever w/c cover the
    live widths, because joined rows keep pads sorted to the tail."""
    pad = _pad_of(batch.dots.dtype)
    return DocBatch(
        _fit(batch.dots, w, pad),
        _fit(batch.pay, w, -1),
        batch.vv,
        _fit(batch.cloud, c, pad),
    )


@jax.jit
def live_widths(batch: DocBatch):
    """(2,) int32: max live dot / cloud width over rows (pads at tails).
    Read at would-widen moments to re-tighten the host width bounds —
    redelivered deltas inflate the bounds but not the live state, and
    this one small pull is what keeps them from forcing spurious plane
    growth (and recompiles)."""
    pad = _pad_of(batch.dots.dtype)
    ld = jnp.max(jnp.sum((batch.dots != pad).astype(I32), axis=-1))
    lc = jnp.max(jnp.sum((batch.cloud != pad).astype(I32), axis=-1))
    return jnp.stack([ld, lc])


@jax.jit
def remap_pay(batch: DocBatch, table) -> DocBatch:
    """Rewrite payload ids through a compaction table (-1 stays -1)."""
    pay = jnp.where(batch.pay >= 0, table[jnp.maximum(batch.pay, 0)], -1)
    return DocBatch(batch.dots, pay, batch.vv, batch.cloud)


@partial(jax.jit, static_argnames=("old_shift",))
def widen_rows(batch: DocBatch, old_shift: int) -> DocBatch:
    """Migrate narrow int32 rows to the u64/32 layout in place on device.

    (col << old_shift | seq) -> (col << 32 | seq) is monotone in (col,
    seq), so row sort order survives; narrow pads map to the u64 pad."""
    mask = (1 << old_shift) - 1

    def w(plane):
        p64 = plane.astype(jnp.uint64)
        repacked = ((p64 >> old_shift) << jnp.uint64(32)) | (
            p64 & jnp.uint64(mask)
        )
        return jnp.where(plane == dev.PAD32, dev.PAD64, repacked)

    return DocBatch(w(batch.dots), batch.pay, batch.vv, w(batch.cloud))


@partial(jax.jit, static_argnames=("old_shift", "new_shift"))
def repack_narrow(batch: DocBatch, old_shift: int, new_shift: int) -> DocBatch:
    """Re-pack int32 rows at a smaller shift (replica-column growth that
    still fits a narrow layout). The caller must have verified every seq
    ever encoded is < 2**new_shift - 1 (strictly: the all-ones seq at the
    top column would collide with the pad). The map is monotone in
    (col, seq), so sorted rows stay sorted."""
    mask = (1 << old_shift) - 1

    def w(plane):
        repacked = ((plane >> old_shift) << new_shift) | (plane & mask)
        return jnp.where(plane == dev.PAD32, dev.PAD32, repacked)

    return DocBatch(w(batch.dots), batch.pay, batch.vv, w(batch.cloud))


@jax.jit
def clear_rows(batch: DocBatch, mask) -> DocBatch:
    """Reset masked rows to the identity document (eviction)."""
    pad = _pad_of(batch.dots.dtype)
    m = mask[:, None]
    return DocBatch(
        jnp.where(m, pad, batch.dots),
        jnp.where(m, -1, batch.pay),
        jnp.where(m, U32(0), batch.vv),
        jnp.where(m, pad, batch.cloud),
    )


@jax.jit
def place_rows(batch: DocBatch, rows: DocBatch, idx) -> DocBatch:
    """Write freshly-encoded rows into free slots (admission). Plane
    widths must already be harmonised by the caller."""
    return DocBatch(
        batch.dots.at[idx].set(rows.dots),
        batch.pay.at[idx].set(rows.pay),
        batch.vv.at[idx].set(rows.vv),
        batch.cloud.at[idx].set(rows.cloud),
    )


@partial(jax.jit, static_argnames=("rows",))
def grow_capacity(batch: DocBatch, rows: int) -> DocBatch:
    """Append identity rows (capacity growth, bucketed by the caller)."""
    pad = _pad_of(batch.dots.dtype)
    k = batch.dots.shape[0]

    def app(plane, fill):
        return jnp.concatenate(
            [plane, jnp.full((rows - k,) + plane.shape[1:], fill, plane.dtype)],
            axis=0,
        )

    return DocBatch(
        app(batch.dots, pad), app(batch.pay, -1), app(batch.vv, 0),
        app(batch.cloud, pad),
    )


@partial(jax.jit, static_argnames=("n_rep",))
def grow_reps(batch: DocBatch, n_rep: int) -> DocBatch:
    """Widen the vv plane for replica-column growth (interner append-only,
    so existing columns keep their meaning)."""
    k, r = batch.vv.shape
    vv = jnp.concatenate(
        [batch.vv, jnp.zeros((k, n_rep - r), U32)], axis=-1
    )
    return DocBatch(batch.dots, batch.pay, vv, batch.cloud)


def _ready(arr) -> bool:
    """True when a device array's host copy would not block."""
    try:
        return arr.is_ready()
    except AttributeError:
        return True  # no readiness API: reading is the only option


# ---- the store -------------------------------------------------------------


class ResidentStore:
    """Hot UJSON keys as device-resident DocBatch rows.

    Host-side bookkeeping: key->row map, free rows, the replica-id and
    payload interners (shared across every row, append-only), the current
    dot layout (shift), and the width upper bounds the fold kernels slice
    to. All device mutations go through the jitted kernels above.
    """

    ROW_BUCKET = 8  # capacity granularity (rows)
    # soft HBM budget for the resident planes: admission stops (keys fall
    # back to the host lattice) once the projected plane bytes cross it.
    # Width growth on already-resident keys is data the host would hold
    # in RAM anyway; admission count is the axis that must not run away
    BYTE_BUDGET = 256 << 20

    def __init__(self, n_rep: int = 8, mesh=None, shard_fn=None):
        self._mesh = mesh
        self._shard_fn = shard_fn  # parallel.shard_docbatch, mesh-bound
        self._nrep = bucket(n_rep, 4)
        self._shift = dev.narrow_shift(self._nrep)
        self._rid_cols: dict[int, int] = {}
        self._pay_ids: dict[tuple, int] = {}
        self._pay_rev: list[tuple] = []
        # canonical-wire-bytes -> pay id mirror (the native wire->planes
        # encoder interns payloads by their wire spans; identical
        # (path, token) pairs have identical canonical encodings)
        self._pay_wire: dict[bytes, int] = {}
        self._rows: dict[bytes, int] = {}
        self._free: list[int] = []
        self._batch: DocBatch | None = None
        # host-side width bounds as a BOUNDED PIPELINE: every fold
        # returns its live widths (async-copied to host at dispatch) and
        # joins the in-flight queue with its growth counts. The bound is
        # base (the newest CONSUMED fold's live, or admission widths) +
        # the growth of everything still in flight. Landed copies are
        # consumed for free; past PIPE_DEPTH the oldest is consumed
        # BLOCKING — which is exactly the backpressure that stops an
        # ever-wider fold backlog from snowballing device work
        self._base_w = 1
        self._base_c = 1
        self._floor_w = 1  # admission widths until the next exact read
        self._floor_c = 1
        self._inflight: list = []  # [(live_arr, grow_w, grow_c), ...]
        # lazily-batched broadcast deltas (fold_in_broadcast): joins
        # commute, so buffered rounds coalesce into ONE (R*D, W) fold at
        # the next read/drain/threshold — amortising the per-dispatch
        # latency that bounded the small-doc anti-entropy stream
        # (round-5 verdict item 5)
        self._bcast_pend: list = []
        # the largest seq ever encoded into the store: a causal context
        # covers its dot store, so the running max over delta vv/cloud
        # seqs bounds every seq on device — which is what makes the
        # narrow->narrow repack on replica growth provably safe
        self._max_seq = 0

    # -- interners ----------------------------------------------------------

    def pay(self, path, token) -> int:
        k = (path, token)
        pid = self._pay_ids.get(k)
        if pid is None:
            pid = self._pay_ids[k] = len(self._pay_rev)
            self._pay_rev.append(k)
        return pid

    def pay_lookup(self, pid: int):
        pt = self._pay_rev[pid]
        if type(pt) is bytes:
            # wire-interned payload: parse its canonical span on first
            # read (the drain never needs the parsed form — only decode
            # paths do, and only for payloads that survive to a read)
            from ..utils.wire import Reader

            r = Reader(pt)
            path = tuple(r.str_() for _ in range(r.varint()))
            pt = (path, r.str_())
            self._pay_rev[pid] = pt
            self._pay_ids.setdefault(pt, pid)
        return pt

    # -- introspection ------------------------------------------------------

    def __contains__(self, key: bytes) -> bool:
        return key in self._rows

    def __len__(self) -> int:
        return len(self._rows)

    def keys(self):
        return self._rows.keys()

    def block(self) -> None:
        """Wait for every queued device mutation (timing/shutdown)."""
        self._flush_broadcast()
        if self._batch is not None:
            jax.block_until_ready(self._batch.dots)

    def approx_bytes(self) -> int:
        """Projected resident plane footprint (current shapes)."""
        if self._batch is None:
            return 0
        return sum(p.size * p.dtype.itemsize for p in self._batch)

    def full(self) -> bool:
        """True when admission should stop (BYTE_BUDGET crossed): the
        serving repo keeps further keys on the host lattice."""
        return self.approx_bytes() >= self.BYTE_BUDGET

    # -- layout plumbing ----------------------------------------------------

    def _row_axis(self) -> int:
        return self._batch.dots.shape[0] if self._batch is not None else 0

    def _capacity_for(self, rows: int) -> int:
        cap = bucket(max(rows, 2), self.ROW_BUCKET)
        if self._mesh is not None:
            m = self._mesh.devices.size
            cap += -cap % m
        return cap

    def _shard(self, batch: DocBatch) -> DocBatch:
        if self._shard_fn is None:
            return batch
        return self._shard_fn(batch)

    PIPE_DEPTH = 2  # folds allowed in flight before blocking on the oldest

    def _consume(self, block: bool) -> bool:
        """Consume the oldest in-flight fold's live widths into the
        base. The consumed fold's own growth is implicitly reflected in
        its measured live, so it leaves the in-flight sum."""
        if not self._inflight:
            return False
        arr, _gw, _gc = self._inflight[0]
        if not block and not _ready(arr):
            return False
        self._inflight.pop(0)
        lw, lc = (int(x) for x in jax.device_get(arr))
        # the floor covers rows admitted after the consumed fold
        # dispatched (their widths are invisible to its live output)
        self._base_w = max(lw, self._floor_w, 1)
        self._base_c = max(lc, self._floor_c, 1)
        return True

    def _budget_widths(self, grow_w: int, grow_c: int) -> tuple[int, int]:
        """Width targets for the next fold. The bound is the newest
        consumed fold's LIVE widths plus the growth counts of everything
        still in flight — an over-estimate whenever joins dedup
        (redelivery) or context compaction absorbs (contiguous dots),
        corrected as soon as a landed live-width copy is consumed. Past
        PIPE_DEPTH the consume BLOCKS: bounded pipelining, so a backlog
        of ever-wider folds can never snowball the device queue."""
        while self._consume(block=False):
            pass
        while len(self._inflight) >= self.PIPE_DEPTH:
            self._consume(block=True)
        ub_w = self._base_w + grow_w + sum(g for _, g, _c in self._inflight)
        ub_c = self._base_c + grow_c + sum(c for _, _g, c in self._inflight)
        if self._batch is None:
            return bucket(ub_w, 4), bucket(ub_c, 4)
        bw = self._batch.dots.shape[-1]
        bc = self._batch.cloud.shape[-1]
        out_w = bucket(ub_w, 4)
        out_c = bucket(ub_c, 4)
        # shape hysteresis: keep the current width unless it must grow
        # or can shrink 4x (no recompile thrash around a boundary)
        if out_w < bw and out_w * 4 > bw:
            out_w = bw
        if out_c < bc and out_c * 4 > bc:
            out_c = bc
        return out_w, out_c

    def _grid_to_device(self, grid: DocBatch) -> DocBatch:
        """Ship grid planes to the device, materialising all-identity
        planes on-device instead of transferring them (a sparse drain's
        vv plane is megabytes of zeros; anti-entropy deltas rarely carry
        vv entries at all — their dots ride in the cloud)."""
        pad = _pad_of(np.asarray(grid.dots).dtype)

        def put(p, fill):
            if isinstance(p, np.ndarray):
                uniform = (not p.any()) if fill == 0 else bool((p == fill).all())
                if uniform:
                    if fill == 0:
                        return jnp.zeros(p.shape, p.dtype)
                    return jnp.full(p.shape, fill, p.dtype)
            return jnp.asarray(p)

        return DocBatch(
            put(grid.dots, pad),
            put(grid.pay, -1),
            put(grid.vv, 0),
            put(grid.cloud, pad),
        )

    def _note_fold(self, batch: DocBatch, live, gw: int, gc: int) -> DocBatch:
        """Enqueue a fold in the bounded pipeline: keep its live-width
        scalars (host copy started in the background) and its growth
        counts for the in-flight bound."""
        self._inflight.append((live, gw, gc))
        try:
            live.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass
        return batch

    def _note_seqs(self, docs) -> None:
        """Track the max seq across delta contexts (context covers store,
        so vv+cloud bound the entries too). Wire deltas carry their
        measured max_seq — reading .ctx would defeat their laziness."""
        m = self._max_seq
        for d in docs:
            ms = getattr(d, "max_seq", None)
            if ms is not None:
                if ms > m:
                    m = ms
                continue
            for s in d.ctx.vv.values():
                if s > m:
                    m = s
            for _, s in d.ctx.cloud:
                if s > m:
                    m = s
        self._max_seq = m

    def _widen(self) -> None:
        if self._shift == 32:
            return
        if self._batch is not None:
            self._batch = self._shard(widen_rows(self._batch, self._shift))
        self._shift = 32

    def _ensure_reps(self) -> None:
        self._grow_reps_to(len(self._rid_cols))

    def _grow_reps_to(self, n: int) -> None:
        """Replica-column growth to at least n: widen vv columns, and
        re-pack the dot layout if the replica-column budget no longer
        fits — to a smaller narrow shift when every seq ever encoded
        still fits it, else to u64/32."""
        if self._shift != 32 and n > (1 << (31 - self._shift)):
            s2 = dev.narrow_shift(bucket(n, 4))
            if self._max_seq < (1 << s2) - 1:
                if self._batch is not None:
                    self._batch = self._shard(
                        repack_narrow(self._batch, self._shift, s2)
                    )
                self._shift = s2
            else:
                self._widen()
        if n > self._nrep:
            self._nrep = bucket(n, 4)
            if self._batch is not None:
                self._batch = self._shard(grow_reps(self._batch, self._nrep))

    def _encode_rows(self, docs) -> DocBatch:
        """Encode host docs at the store's current layout, migrating the
        store when the narrow layout can't hold them. OverflowError
        escapes only when even u64/32 can't (seq past u32)."""
        while True:
            try:
                b = dev._encode_docs_np(
                    docs, self._rid_cols, self.pay, self._nrep, shift=self._shift
                )
            except OverflowError:
                if self._shift == 32:
                    raise
                self._widen()
                continue
            except ValueError:  # rid interner outgrew the vv budget
                self._ensure_reps()
                continue
            # a successful encode at self._nrep proves the interner fits
            # it (the encoder checks); _ensure_reps only handles the
            # narrow-shift budget here
            self._ensure_reps()
            return b

    def _encode_grid(self, groups) -> DocBatch:
        wire = self._grid_from_wire(groups)
        if wire is not None:
            return wire
        while True:
            try:
                g = dev.encode_doc_groups(
                    groups, self._rid_cols, self.pay, self._nrep,
                    shift=self._shift,
                )
            except OverflowError:
                if self._shift == 32:
                    raise
                self._widen()
                continue
            except ValueError:
                self._ensure_reps()
                continue
            self._ensure_reps()
            return g

    def _grid_from_wire(self, groups) -> DocBatch | None:
        """The native wire->planes grid encoder: when every delta in the
        drain is a WireUJSON (the cluster receive path), the (K, D, W)
        grid fills straight from the raw payload bytes — per-delta host
        cost is native parsing + interning, no Python dict walks. Returns
        None (caller uses the object encoder) when the native library is
        missing or any delta is a plain document."""
        from ..native import lib
        from .ujson_wire import (
            GridOverflow,
            GridRepBudget,
            WireUJSON,
            grid_from_wire,
        )

        if lib() is None:
            return None
        flat = []
        for g in groups:
            for d in g:
                if type(d) is not WireUJSON:
                    return None
                flat.append(d)
        if not flat:
            return None
        d_dim = bucket(max(len(g) for g in groups), 1)
        w = bucket(max(max(d.n_entries for d in flat), 1), 4)
        c = bucket(max(max(d.n_cloud for d in flat), 1), 4)
        rows = len(groups) * d_dim
        dest = np.fromiter(
            (
                k * d_dim + j
                for k, g in enumerate(groups)
                for j in range(len(g))
            ),
            np.int64,
            count=len(flat),
        )
        while True:
            known = [0] * len(self._rid_cols)
            for rid, col in self._rid_cols.items():
                known[col] = rid
            try:
                dots, pay, vv, cloud, new_rids, spans = grid_from_wire(
                    flat, dest, rows, w, c, self._shift, self._nrep, known
                )
            except GridOverflow:
                if self._shift == 32:
                    raise OverflowError("seq beyond the u64/32 layout")
                self._widen()
                continue
            except GridRepBudget as e:
                self._grow_reps_to(e.needed)
                continue
            break
        for rid in new_rids:
            self._rid_cols[rid] = len(self._rid_cols)
        self._ensure_reps()
        if self._nrep > vv.shape[-1]:
            # new columns crossed a vv bucket AFTER a successful fill:
            # widen the grid's vv plane to match the store
            vv = np.concatenate(
                [vv, np.zeros((rows, self._nrep - vv.shape[-1]), np.uint32)],
                axis=-1,
            )
        if spans:
            # new payloads intern by their canonical span; parsing to
            # (path, token) is deferred to pay_lookup (reads). A payload
            # that later ALSO arrives via the object path gets a second
            # id — harmless (ids just name payloads; dots dedup joins)
            lut = np.empty(len(spans), np.int32)
            pw = self._pay_wire
            rev = self._pay_rev
            for i, span in enumerate(spans):
                gid = pw.get(span)
                if gid is None:
                    gid = pw[span] = len(rev)
                    rev.append(span)
                lut[i] = gid
            pay = np.where(pay >= 0, lut[np.maximum(pay, 0)], -1)
        k = len(groups)
        return DocBatch(
            dots.reshape(k, d_dim, w),
            pay.reshape(k, d_dim, w),
            vv.reshape(k, d_dim, self._nrep),
            cloud.reshape(k, d_dim, c),
        )

    # -- admission / eviction ------------------------------------------------

    def admit(self, items: list[tuple[bytes, object]]) -> None:
        """Make keys resident with their current host docs (encoded ONCE;
        after this only reads ever decode them again)."""
        # buffered broadcasts target the rows present when they arrived
        self._flush_broadcast()
        items = [(k, d) for k, d in items if k not in self._rows]
        if not items:
            return
        self._note_seqs([d for _, d in items])
        # entries are not covered by _note_seqs' vv/cloud shortcut for
        # admitted FULL docs only in theory; the ORSWOT invariant (ctx
        # covers store) holds for every doc the host lattice builds, so
        # vv alone still bounds them
        rows_np = self._encode_rows([d for _, d in items])
        self._base_w = max(self._base_w, rows_np.dots.shape[-1])
        self._base_c = max(self._base_c, rows_np.cloud.shape[-1])
        # admitted rows can exceed any in-flight fold's live widths; the
        # floor survives consumes until the next exact full read
        self._floor_w = max(self._floor_w, rows_np.dots.shape[-1])
        self._floor_c = max(self._floor_c, rows_np.cloud.shape[-1])
        if self._batch is None:
            cap = self._capacity_for(len(items) + 1)
            pad = _pad_of(np.int32 if self._shift < 32 else np.uint64)
            dtype = np.int32 if self._shift < 32 else np.uint64
            w = rows_np.dots.shape[-1]
            c = rows_np.cloud.shape[-1]
            self._batch = self._shard(
                DocBatch(
                    jnp.asarray(np.full((cap, w), pad, dtype)),
                    jnp.asarray(np.full((cap, w), -1, np.int32)),
                    jnp.asarray(np.zeros((cap, self._nrep), np.uint32)),
                    jnp.asarray(np.full((cap, c), pad, dtype)),
                )
            )
            self._free = list(range(cap - 1, 0, -1))  # row 0 is scratch
        need = len(items)
        if len(self._free) < need:
            old = self._row_axis()
            cap = self._capacity_for(old + need - len(self._free))
            self._batch = self._shard(grow_capacity(self._batch, cap))
            self._free = list(range(cap - 1, old - 1, -1)) + self._free
        # harmonise widths between the resident planes and the new rows
        bw, bc = self._batch.dots.shape[-1], self._batch.cloud.shape[-1]
        rw, rc = rows_np.dots.shape[-1], rows_np.cloud.shape[-1]
        if rw > bw or rc > bc:
            self._batch = self._shard(
                slice_widths(self._batch, max(rw, bw), max(rc, bc))
            )
            bw, bc = max(rw, bw), max(rc, bc)
        if rw < bw or rc < bc:
            rows_np = _pad_planes_np(rows_np, bw, bc)
        idx = np.empty(need, np.int32)
        for j, (key, _) in enumerate(items):
            row = self._free.pop()
            self._rows[key] = row
            idx[j] = row
        self._batch = self._shard(
            place_rows(self._batch, DocBatch(*(jnp.asarray(p) for p in rows_np)),
                       jnp.asarray(idx))
        )

    def evict(self, key: bytes):
        """Decode a key's current doc and drop its row (demotion to the
        host lattice, e.g. before a local write)."""
        doc = self.read(key)
        self.discard(key)
        return doc

    def discard(self, key: bytes) -> None:
        """Drop a key's row WITHOUT decoding (the caller already holds a
        current host view, e.g. the serving repo's read cache)."""
        self._flush_broadcast()  # the departing row must absorb its share
        row = self._rows.pop(key)
        mask = np.zeros(self._row_axis(), bool)
        mask[row] = True
        self._batch = self._shard(clear_rows(self._batch, jnp.asarray(mask)))
        self._free.append(row)

    # -- the drain ----------------------------------------------------------

    def fold_in(self, pending: dict[bytes, list]) -> None:
        """Fold each key's pending deltas into its resident row — ONE
        device dispatch for every key in the drain, no host read-backs.
        Raises OverflowError (rows unchanged) when a delta exceeds the
        u64/32 layout; the caller demotes those keys to the host
        lattice."""
        self._flush_broadcast()
        pending = {k: v for k, v in pending.items() if v and k in self._rows}
        if not pending:
            return
        self._note_seqs([d for lst in pending.values() for d in lst])
        # width bound: each row grows by at most its group's entry/cloud
        # counts (the join can only drop), so the batch max grows by at
        # most the largest group's counts. Wire deltas carry measured
        # counts; touching .entries would materialise them
        grow_w = grow_c = 0
        for lst in pending.values():
            ew = ec = 0
            for d in lst:
                n = getattr(d, "n_entries", None)
                if n is not None:
                    ew += n
                    ec += d.n_cloud
                else:
                    ew += len(d.entries)
                    ec += len(d.ctx.cloud)
            if ew > grow_w:
                grow_w = ew
            if ec > grow_c:
                grow_c = ec
        if self._mesh is None:
            # single device: the subset fold's grid covers exactly the
            # drained keys (the aligned grid spans every capacity row —
            # only worth it when sharding forbids gathers/scatters)
            self._fold_subset(pending, grow_w, grow_c)
        else:
            self._fold_aligned(pending, grow_w, grow_c)

    # buffered broadcast deltas past this count force a flush, bounding
    # host memory and the single fold's delta axis. Measured on the
    # 32-replica stream (bench.py --config ujson-32): coalescing is
    # monotonically better through 10k+ deltas (one 10240-delta fold
    # beats two 5120-delta folds ~1.3x and eager per-round folds ~2.4x),
    # so the cap is a memory/width bound, not a performance knob
    BCAST_FLUSH_DELTAS = 16384

    def fold_in_broadcast(self, deltas: list) -> None:
        """Fold one delta list into EVERY resident row (the all-replicas
        anti-entropy shape). Same contracts as fold_in, but LAZY: the
        join is commutative and associative, so consecutive rounds buffer
        and coalesce into one (R*D, W) fold at the next read, per-key
        drain, admission/eviction, or threshold — one dispatch where the
        eager path paid one per round."""
        if not deltas or not self._rows:
            return
        self._note_seqs(deltas)
        self._bcast_pend.extend(deltas)
        if len(self._bcast_pend) >= self.BCAST_FLUSH_DELTAS:
            self._flush_broadcast()

    def _flush_broadcast(self) -> None:
        if not self._bcast_pend:
            return
        deltas, self._bcast_pend = self._bcast_pend, []
        if not self._rows:
            return
        from .ujson_host import UJSON
        # wire path: the whole list as ONE (1, D, W) grid segment
        grid = self._grid_from_wire([list(deltas)])
        if grid is not None:
            batch = self._grid_to_device(DocBatch(*(p[0] for p in grid)))
        else:
            d = bucket(len(deltas), 4)  # identity-pad: bound the jit cache
            rows_np = self._encode_rows(
                list(deltas) + [UJSON()] * (d - len(deltas))
            )
            batch = DocBatch(*(jnp.asarray(p) for p in rows_np))
        grow_w = grow_c = 0
        for x in deltas:
            n = getattr(x, "n_entries", None)
            if n is not None:
                grow_w += n
                grow_c += x.n_cloud
            else:
                grow_w += len(x.entries)
                grow_c += len(x.ctx.cloud)
        out_w, out_c = self._budget_widths(grow_w, grow_c)
        occ = np.zeros(self._row_axis(), bool)
        occ[list(self._rows.values())] = True
        # the delta batch's leading axis is deltas, not resident rows;
        # it stays replicated (only the resident planes are row-sharded)
        out, live = fold_broadcast_rows(
            self._batch, batch, jnp.asarray(occ),
            shift=self._shift, out_w=out_w, out_c=out_c,
        )
        self._batch = self._shard(self._note_fold(out, live, grow_w, grow_c))

    def _fold_subset(self, pending, grow_w: int, grow_c: int) -> None:
        ks = sorted(pending)
        n = bucket(len(ks), 4)
        groups = [pending[k] for k in ks] + [[] for _ in range(n - len(ks))]
        grid = self._encode_grid(groups)
        out_w, out_c = self._budget_widths(grow_w, grow_c)
        idx = np.zeros(n, np.int32)  # pad slots -> scratch row 0
        for j, k in enumerate(ks):
            idx[j] = self._rows[k]
        grid = self._grid_to_device(grid)
        out, live = fold_join_subset(
            self._batch, grid, jnp.asarray(idx), shift=self._shift,
            out_w=out_w, out_c=out_c,
        )
        self._batch = self._note_fold(out, live, grow_w, grow_c)

    def _fold_aligned(self, pending, grow_w: int, grow_c: int) -> None:
        cap = self._row_axis()
        groups: list[list] = [[] for _ in range(cap)]
        for k, lst in pending.items():
            groups[self._rows[k]] = lst
        grid = self._encode_grid(groups)
        out_w, out_c = self._budget_widths(grow_w, grow_c)
        grid = self._shard(self._grid_to_device(grid))
        out, live = fold_join_aligned(
            self._batch, grid, shift=self._shift, out_w=out_w, out_c=out_c
        )
        self._batch = self._shard(self._note_fold(out, live, grow_w, grow_c))

    # -- reads ---------------------------------------------------------------

    def read(self, key: bytes):
        """Decode ONE key's doc (device->host pull of its row slices)."""
        return self.read_many([key])[0]

    def read_many(self, keys: list[bytes]) -> list:
        self._flush_broadcast()
        rows = jnp.asarray(
            np.array([self._rows[k] for k in keys], np.int32)
        )
        sub = DocBatch(*(p[rows] for p in self._batch))
        np_sub = DocBatch(*jax.device_get(tuple(sub)))  # one transfer
        # full-read detection must reject duplicate keys: a duplicated
        # subset could pass the length check and re-tighten (then slice)
        # below an unread row's live width
        if len(keys) == len(self._rows) and len(set(keys)) == len(keys):
            # a full read pulled every row anyway: re-tighten the width
            # bounds (and re-bucket the planes) for free
            pad = _pad_of(np_sub.dots.dtype)
            self._base_w = max(int((np_sub.dots != pad).sum(axis=1).max()), 1)
            self._base_c = max(int((np_sub.cloud != pad).sum(axis=1).max()), 1)
            self._inflight.clear()  # the pull reflects every queued fold
            self._floor_w = self._floor_c = 1
            w = bucket(self._base_w, 4)
            c = bucket(self._base_c, 4)
            if (
                w < self._batch.dots.shape[-1]
                or c < self._batch.cloud.shape[-1]
            ):
                self._batch = self._shard(slice_widths(self._batch, w, c))
        cols_rid = {c: r for r, c in self._rid_cols.items()}
        docs = dev.decode_batch(
            np_sub, cols_rid, self.pay_lookup, shift=self._shift
        )
        if len(keys) == len(self._rows) and len(set(keys)) == len(keys):
            self._compact_pay(np_sub)
        return docs

    def _compact_pay(self, np_sub: DocBatch) -> None:
        """Payload-interner epoch compaction (the ops/interner.py hazard:
        append-only tables leak under value churn). Runs on full reads —
        the pulled pay planes ARE the live-id census — when dead ids
        dominate: rebuild the interner from the live ids and remap the
        device plane through a table in one dispatch."""
        live = np.unique(np_sub.pay)
        live = live[live >= 0]
        if len(self._pay_rev) <= 2 * max(len(live), 16):
            return
        table = np.full(len(self._pay_rev), -1, np.int32)
        new_rev = []
        for pid in live:
            table[pid] = len(new_rev)
            new_rev.append(self._pay_rev[pid])
        self._pay_rev = new_rev
        self._pay_ids = {k: i for i, k in enumerate(new_rev)}
        self._pay_wire = {
            span: int(table[pid])
            for span, pid in self._pay_wire.items()
            if table[pid] >= 0
        }
        self._batch = self._shard(remap_pay(self._batch, jnp.asarray(table)))

    def dump(self) -> list[tuple[bytes, object]]:
        """Decode every resident key (snapshots / bootstrap sync)."""
        if not self._rows:
            return []
        keys = sorted(self._rows)
        return list(zip(keys, self.read_many(keys)))


def _pad_planes_np(batch: DocBatch, w: int, c: int) -> DocBatch:
    pad = _pad_of(batch.dots.dtype)
    k = batch.dots.shape[0]

    def padto(plane, width, fill):
        extra = width - plane.shape[-1]
        if extra <= 0:
            return plane
        return np.concatenate(
            [plane, np.full((k, extra), fill, plane.dtype)], axis=-1
        )

    return DocBatch(
        padto(batch.dots, w, pad), padto(batch.pay, w, -1), batch.vv,
        padto(batch.cloud, c, pad),
    )
