"""PNCOUNT: positive/negative counter lattice as batched TPU kernels.

Semantics (docs/_docs/types/pncount.md:49-55): two grow-only per-replica
maps, P and N, converged independently by per-replica max; the value is
sum(P) - sum(N) as a signed 64-bit integer. Reference repo:
jylis/repo_pncount.pony:26-67 (INC grows P, DEC grows N, GET nets them).

Layout mirrors gcount: two (K, R) uint64 tensors; batched converge is two
scatter-max ops. This type is the north-star benchmark target
(BASELINE.json: 1M-key, 64-replica anti-entropy).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

UINT64 = jnp.uint64


class PNCountState(NamedTuple):
    p: jax.Array  # (K, R) uint64 — increments per replica
    n: jax.Array  # (K, R) uint64 — decrements per replica


def init(num_keys: int, num_replicas: int) -> PNCountState:
    # two distinct buffers: the drain path donates the state, and XLA
    # rejects donating one aliased buffer twice
    return PNCountState(
        jnp.zeros((num_keys, num_replicas), UINT64),
        jnp.zeros((num_keys, num_replicas), UINT64),
    )


def join(a: PNCountState, b: PNCountState) -> PNCountState:
    return PNCountState(jnp.maximum(a.p, b.p), jnp.maximum(a.n, b.n))


def converge_batch(
    state: PNCountState,
    key_idx: jax.Array,
    delta_p: jax.Array,
    delta_n: jax.Array,
) -> PNCountState:
    """Join a delta batch: (B,) key rows, (B, R) joinable P and N deltas."""
    return PNCountState(
        state.p.at[key_idx].max(delta_p, mode="drop"),
        state.n.at[key_idx].max(delta_n, mode="drop"),
    )


def increment(
    state: PNCountState, key_idx: jax.Array, replica_idx: jax.Array, amount: jax.Array
) -> PNCountState:
    return PNCountState(
        state.p.at[key_idx, replica_idx].add(amount, mode="drop"), state.n
    )


def decrement(
    state: PNCountState, key_idx: jax.Array, replica_idx: jax.Array, amount: jax.Array
) -> PNCountState:
    return PNCountState(
        state.p, state.n.at[key_idx, replica_idx].add(amount, mode="drop")
    )


def read(state: PNCountState, key_idx: jax.Array) -> jax.Array:
    """GET for a batch of keys: signed net value.

    Computed with u64 wraparound then bitcast to int64, matching the
    reference's Pony (p_sum - n_sum).i64() modular behavior
    (repo_pncount.pony:55-57).
    """
    p = jnp.sum(state.p[key_idx], axis=-1, dtype=UINT64)
    n = jnp.sum(state.n[key_idx], axis=-1, dtype=UINT64)
    return jax.lax.bitcast_convert_type(p - n, jnp.int64)


def read_all(state: PNCountState) -> jax.Array:
    p = jnp.sum(state.p, axis=-1, dtype=UINT64)
    n = jnp.sum(state.n, axis=-1, dtype=UINT64)
    return jax.lax.bitcast_convert_type(p - n, jnp.int64)


def grow(state: PNCountState, num_keys: int, num_replicas: int) -> PNCountState:
    k, r = state.p.shape
    if num_keys == k and num_replicas == r:
        return state
    z = jnp.zeros((num_keys, num_replicas), UINT64)
    return PNCountState(z.at[:k, :r].set(state.p), z.at[:k, :r].set(state.n))
