"""PNCOUNT: positive/negative counter lattice as batched TPU kernels.

Semantics (docs/_docs/types/pncount.md:49-55): two grow-only per-replica
maps, P and N, converged independently by per-replica max; the value is
sum(P) - sum(N) as a signed 64-bit integer. Reference repo:
jylis/repo_pncount.pony:26-67 (INC grows P, DEC grows N, GET nets them).

Layout mirrors gcount: each polarity is a (K, R) u64 tensor stored as
hi/lo u32 planes (ops/planes.py); batched converge is two gather->joint
max->scatter composites. This type is the north-star benchmark target
(BASELINE.json: 1M-key, 64-replica anti-entropy). Batches must carry
UNIQUE key rows (serving repos guarantee it via their pending dicts).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import planes

U32 = jnp.uint32
U64 = jnp.uint64
I64 = jnp.int64


class PNCountState(NamedTuple):
    p_hi: jax.Array  # (K, R) uint32
    p_lo: jax.Array
    n_hi: jax.Array
    n_lo: jax.Array


def init(num_keys: int, num_replicas: int) -> PNCountState:
    # distinct buffers: the drain path donates the state, and XLA rejects
    # donating one aliased buffer twice
    return PNCountState(
        *(jnp.zeros((num_keys, num_replicas), U32) for _ in range(4))
    )


def from_counts(p, n) -> PNCountState:
    p_hi, p_lo = planes.split64_np(np.asarray(p))
    n_hi, n_lo = planes.split64_np(np.asarray(n))
    return PNCountState(
        jnp.asarray(p_hi), jnp.asarray(p_lo), jnp.asarray(n_hi), jnp.asarray(n_lo)
    )


def join(a: PNCountState, b: PNCountState) -> PNCountState:
    p = planes.join_max(a.p_hi, a.p_lo, b.p_hi, b.p_lo)
    n = planes.join_max(a.n_hi, a.n_lo, b.n_hi, b.n_lo)
    return PNCountState(p[0], p[1], n[0], n[1])


def converge_batch(
    state: PNCountState,
    key_idx: jax.Array,
    dp_hi: jax.Array,
    dp_lo: jax.Array,
    dn_hi: jax.Array,
    dn_lo: jax.Array,
) -> PNCountState:
    """Join a delta batch at UNIQUE (B,) key rows; (B, R) u32 planes per
    polarity."""
    p = planes.scatter_join(state.p_hi, state.p_lo, key_idx, dp_hi, dp_lo)
    n = planes.scatter_join(state.n_hi, state.n_lo, key_idx, dn_hi, dn_lo)
    return PNCountState(p[0], p[1], n[0], n[1])


def _bump(hi, lo, key_idx, replica_idx, amount):
    a_hi = (amount >> jnp.uint64(32)).astype(U32)
    a_lo = amount.astype(U32)
    new_hi, new_lo = planes.add_carry(
        hi[key_idx, replica_idx], lo[key_idx, replica_idx], a_hi, a_lo
    )
    return (
        hi.at[key_idx, replica_idx].set(new_hi, mode="drop", unique_indices=True),
        lo.at[key_idx, replica_idx].set(new_lo, mode="drop", unique_indices=True),
    )


def increment(
    state: PNCountState, key_idx: jax.Array, replica_idx: jax.Array, amount: jax.Array
) -> PNCountState:
    """INC at UNIQUE (key, replica) coordinates; amount (B,) uint64."""
    p_hi, p_lo = _bump(state.p_hi, state.p_lo, key_idx, replica_idx, amount)
    return PNCountState(p_hi, p_lo, state.n_hi, state.n_lo)


def decrement(
    state: PNCountState, key_idx: jax.Array, replica_idx: jax.Array, amount: jax.Array
) -> PNCountState:
    n_hi, n_lo = _bump(state.n_hi, state.n_lo, key_idx, replica_idx, amount)
    return PNCountState(state.p_hi, state.p_lo, n_hi, n_lo)


def read(state: PNCountState, key_idx: jax.Array) -> jax.Array:
    """GET for a batch of keys: signed net value.

    Computed with u64 wraparound then bitcast to int64, matching the
    reference's Pony (p_sum - n_sum).i64() modular behavior
    (repo_pncount.pony:55-57).
    """
    p = planes.rowsum64(state.p_hi[key_idx], state.p_lo[key_idx])
    n = planes.rowsum64(state.n_hi[key_idx], state.n_lo[key_idx])
    return jax.lax.bitcast_convert_type(p - n, I64)


def read_all(state: PNCountState) -> jax.Array:
    p = planes.rowsum64(state.p_hi, state.p_lo)
    n = planes.rowsum64(state.n_hi, state.n_lo)
    return jax.lax.bitcast_convert_type(p - n, I64)


def grow(state: PNCountState, num_keys: int, num_replicas: int) -> PNCountState:
    k, r = state.p_hi.shape
    if num_keys == k and num_replicas == r:
        return state
    z = jnp.zeros((num_keys, num_replicas), U32)
    return PNCountState(
        z.at[:k, :r].set(state.p_hi),
        z.at[:k, :r].set(state.p_lo),
        z.at[:k, :r].set(state.n_hi),
        z.at[:k, :r].set(state.n_lo),
    )
