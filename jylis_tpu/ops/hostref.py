"""Pure-Python reference lattices.

Direct, obviously-correct implementations of the documented CRDT semantics
(docs/_docs/types/*.md "Detailed Semantics"). Three jobs:

1. differential-test oracle for the device kernels (tests/),
2. the CPU baseline the benchmark compares against (bench.py),
3. the SYSTEM log's tiny single-key TLog (models/repo_system.py), where a
   device round-trip would be absurd.

These are NOT the serving path — the serving path is the device kernels.
"""

from __future__ import annotations

from dataclasses import dataclass, field


class GCounter:
    """Replica-id -> u64 map; join = per-id max; value = wrapping sum.

    Semantics: docs/_docs/types/gcount.md:43-47.
    """

    __slots__ = ("counts",)
    _MASK = (1 << 64) - 1

    def __init__(self):
        self.counts: dict[int, int] = {}

    def increment(self, replica: int, amount: int) -> None:
        self.counts[replica] = (self.counts.get(replica, 0) + amount) & self._MASK

    def value(self) -> int:
        return sum(self.counts.values()) & self._MASK

    def converge(self, other: "GCounter") -> bool:
        changed = False
        for rid, v in other.counts.items():
            if v > self.counts.get(rid, -1):
                self.counts[rid] = v
                changed = True
        return changed


class PNCounter:
    """Two GCounters; value = P - N as signed 64-bit (modular).

    Semantics: docs/_docs/types/pncount.md:49-55.
    """

    __slots__ = ("p", "n")

    def __init__(self):
        self.p = GCounter()
        self.n = GCounter()

    def increment(self, replica: int, amount: int) -> None:
        self.p.increment(replica, amount)

    def decrement(self, replica: int, amount: int) -> None:
        self.n.increment(replica, amount)

    def value(self) -> int:
        raw = (self.p.value() - self.n.value()) & ((1 << 64) - 1)
        return raw - (1 << 64) if raw >= (1 << 63) else raw

    def converge(self, other: "PNCounter") -> bool:
        a = self.p.converge(other.p)
        b = self.n.converge(other.n)
        return a or b


class TReg:
    """LWW register over (value: bytes, ts: u64).

    Pair A beats B iff ts_A > ts_B or (ts equal and value_A > value_B
    bytewise) — docs/_docs/types/treg.md:60-63. Unset is (b"", 0) and loses
    to any written pair (a written pair at ts 0 with value b"" equals it).
    """

    __slots__ = ("value", "ts", "is_set")

    def __init__(self):
        self.value: bytes = b""
        self.ts: int = 0
        self.is_set = False

    def write(self, value: bytes, ts: int) -> None:
        if not self.is_set or (ts, value) > (self.ts, self.value):
            self.value, self.ts, self.is_set = value, ts, True

    def read(self):
        return (self.value, self.ts) if self.is_set else None

    def converge(self, other: "TReg") -> bool:
        if other.is_set and (
            not self.is_set or (other.ts, other.value) > (self.ts, self.value)
        ):
            self.value, self.ts, self.is_set = other.value, other.ts, True
            return True
        return False


@dataclass
class TLog:
    """Timestamp-sorted log with grow-only cutoff.

    Entries are (value: bytes, ts: u64), sorted ts desc then value desc;
    duplicates (equal ts AND value) are dropped; entries with ts < cutoff
    are dropped; cutoffs merge by max — docs/_docs/types/tlog.md:116-133.
    """

    entries: list[tuple[bytes, int]] = field(default_factory=list)
    cutoff: int = 0

    def insert(self, value: bytes, ts: int) -> bool:
        if ts < self.cutoff or (value, ts) in self.entries:
            return False
        self.entries.append((value, ts))
        self.entries.sort(key=lambda e: (e[1], e[0]), reverse=True)
        return True

    def size(self) -> int:
        return len(self.entries)

    def latest(self, count: int | None = None) -> list[tuple[bytes, int]]:
        return self.entries if count is None else self.entries[:count]

    def trim(self, count: int) -> None:
        """Raise cutoff to ts of entry at index count-1 (tlog.md:54-60);
        count 0 behaves like clear; negative counts are a no-op (the
        reference parses count as unsigned)."""
        if count == 0:
            self.clear()
        elif 0 < count <= len(self.entries):
            self.raise_cutoff(self.entries[count - 1][1])

    def raise_cutoff(self, ts: int) -> None:
        if ts > self.cutoff:
            self.cutoff = ts
            self.entries = [e for e in self.entries if e[1] >= self.cutoff]

    def clear(self) -> None:
        """Cutoff = latest ts + 1; no-op on an empty log (tlog.md:62-66)."""
        if self.entries:
            self.raise_cutoff(self.entries[0][1] + 1)

    def converge(self, other: "TLog") -> bool:
        before = (len(self.entries), self.cutoff)
        merged = set(self.entries) | set(other.entries)
        self.cutoff = max(self.cutoff, other.cutoff)
        self.entries = sorted(
            (e for e in merged if e[1] >= self.cutoff),
            key=lambda e: (e[1], e[0]),
            reverse=True,
        )
        return (len(self.entries), self.cutoff) != before
