"""P2Set: two-phase set lattice (host-side).

Used solely for cluster membership (reference: cluster.pony:14 keeps
known addresses in a P2Set so that a removed element can never re-appear —
that permanence is what makes stale-name blacklisting work,
cluster.pony:215-230). Data volume is a handful of addresses, so this
lattice stays on host; it is part of the CRDT inventory (SURVEY.md
section 2.9) nonetheless.

Join: adds = adds_a | adds_b; removes = removes_a | removes_b; membership =
adds - removes. Once removed, an element is permanently dead.
"""

from __future__ import annotations

from typing import Generic, Hashable, Iterator, TypeVar

T = TypeVar("T", bound=Hashable)


class P2Set(Generic[T]):
    __slots__ = ("adds", "removes")

    def __init__(self, initial=()):
        self.adds: set[T] = set(initial)
        self.removes: set[T] = set()

    def add(self, item: T) -> bool:
        """Returns False if the item is tombstoned (can never re-join)."""
        self.adds.add(item)
        return item not in self.removes

    def unset(self, item: T) -> None:
        """Permanent removal (tombstone)."""
        self.adds.add(item)
        self.removes.add(item)

    def __contains__(self, item: T) -> bool:
        return item in self.adds and item not in self.removes

    def __iter__(self) -> Iterator[T]:
        return iter(self.adds - self.removes)

    def __len__(self) -> int:
        return len(self.adds - self.removes)

    def converge(self, other: "P2Set[T]") -> bool:
        before = (len(self.adds), len(self.removes))
        self.adds |= other.adds
        self.removes |= other.removes
        return (len(self.adds), len(self.removes)) != before

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, P2Set)
            and self.adds == other.adds
            and self.removes == other.removes
        )

    # mutable lattice: deliberately unhashable (messages carrying one are
    # compared by value, never used as dict/set keys)
    __hash__ = None

    def copy(self) -> "P2Set[T]":
        out = P2Set()
        out.adds = set(self.adds)
        out.removes = set(self.removes)
        return out
