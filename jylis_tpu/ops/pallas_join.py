"""Pallas fused PNCOUNT dense-join kernel — built, measured, and NOT the
default, with the numbers to show why.

Round-1 review hypothesised a hand-written Pallas merge kernel (stream
each state block once, input/output aliasing) would beat the XLA
gather→max→scatter composite ~3× (a hypothesis, never measured). The
real win turned out to be algorithmic: routing full-sweep batches
through the DENSE elementwise join (`pncount.join` under `jit` with
donation) lets XLA emit a single fused streaming loop that measures
162.5M merges/sec/chip recorded on the 1M×64 north star
(BENCH_full.json `north-star`) — near the v5e HBM roofline.

This module is the Pallas version of that dense join, kept for three
reasons: (a) it proves the claim with a measurement instead of a guess —
same workload, 47.2M merges/sec recorded (BENCH_full.json
`pallas-join`; the (K,64)→(N/128,128) relayout XLA
inserts around the custom call costs more than the kernel saves, and the
kernel itself cannot beat a bandwidth bound XLA already hits); (b) it is
the template for future ops that genuinely need manual scheduling
(anything with data-dependent masking XLA refuses to fuse); (c) it
exercises the Mosaic toolchain quirks this environment has, documented
here so the next kernel doesn't rediscover them:

* Mosaic on this toolchain cannot legalise ``arith.maxui`` — express u64
  max as unsigned compares + selects (which DO legalise), not
  ``jnp.maximum`` on uint32.
* The framework runs with ``jax_enable_x64`` on (the lattices are u64);
  Mosaic fails to compile under x64 (i64 grid indices). Trace the
  ``pallas_call`` inside ``jax.enable_x64(False)`` — kernel dtypes here
  are all explicit u32, so semantics are unchanged.
* Block shapes must divide the operand; the flat (N/128, 128) view only
  exists when N % 128 == 0 (callers guarantee power-of-two R).

**Round-4 decision (verdict item 7): this module is kept as a measured
baseline ONLY, and the TLOG sort is explicitly NOT getting a Pallas
kernel.** The TLOG merge's ceiling is its sort network, and the one
hypothesis under which manual scheduling could win — a fused
merge+dedup single pass — loses to the same physics this kernel
measured: ``lax.sort`` keeps each row resident in VMEM across all
compare-exchange stages, while a hand-staged Pallas network at TLOG's
ragged row widths (bucketed 16..64k, many live shapes) would stream
HBM between stages it cannot keep resident, and round-1's layout
measurements put HBM-staged exchange at 40-70x slower than the fused
XLA sort. The dedup fusion saves one elementwise pass over data the
sort already bounds — marginal against a sort-dominated profile, and
against it every TLOG width bucket would need its own hand-tuned
block shape. The recorded `pallas-join` bench config (BENCH_full.json,
0.3x vs the XLA dense join) stays as the standing quantitative
evidence for this class of decision.

Reference analog: none — the reference's merge loop is per-key Pony
(repo_pncount.pony:59-62); this is purely a TPU-side design artifact.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# jax.enable_x64 is the public spelling on newer releases; older
# toolchains (e.g. 0.4.37, the container's pin) ship the same context
# manager as jax.experimental.enable_x64
if hasattr(jax, "enable_x64"):
    enable_x64 = jax.enable_x64
else:  # pragma: no cover - exercised only on older jax pins
    from jax.experimental import enable_x64

from . import pncount

LANES = 128
BLOCK_ROWS = 400  # 400×128×4B×12 planes ≈ 2.5 MB of VMEM per grid step


def _join_kernel(ph, plo, nh, nl, dph, dpl, dnh, dnl, oph, opl, onh, onl):
    # two independent polarity joins; each is a lexicographic (hi, lo)
    # u64 max over u32 plane pairs — compare/select only (see module doc)
    for ah_r, al_r, bh_r, bl_r, oh_r, ol_r in (
        (ph, plo, dph, dpl, oph, opl),
        (nh, nl, dnh, dnl, onh, onl),
    ):
        ah, al = ah_r[...], al_r[...]
        bh, bl = bh_r[...], bl_r[...]
        take = (bh > ah) | ((bh == ah) & (bl > al))
        oh_r[...] = jnp.where(take, bh, ah)
        ol_r[...] = jnp.where(take, bl, al)


def supported(state: pncount.PNCountState) -> bool:
    k, r = state.p_hi.shape
    n = k * r
    return n % LANES == 0 and (n // LANES) % BLOCK_ROWS == 0


@partial(jax.jit, static_argnames=("interpret",), donate_argnums=(0,))
def join_fused(
    state: pncount.PNCountState,
    deltas: pncount.PNCountState,
    interpret: bool = False,
) -> pncount.PNCountState:
    """Dense PN lattice join as one Pallas launch with state aliasing.

    Semantically identical to ``pncount.join``; see module docstring for
    why the XLA path stays the production default. ``interpret=True``
    runs the kernel in pure-JAX interpret mode (how CPU tests check it
    against the oracle without a TPU)."""
    k, r = state.p_hi.shape
    rows = (k * r) // LANES
    planes = [x.reshape(rows, LANES) for x in (*state, *deltas)]
    spec = pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0))
    with enable_x64(False):
        out = pl.pallas_call(
            _join_kernel,
            grid=(rows // BLOCK_ROWS,),
            in_specs=[spec] * 8,
            out_specs=[spec] * 4,
            out_shape=[jax.ShapeDtypeStruct((rows, LANES), jnp.uint32)] * 4,
            input_output_aliases={0: 0, 1: 1, 2: 2, 3: 3},
            interpret=interpret,
        )(*planes)
    return pncount.PNCountState(*(x.reshape(k, r) for x in out))
