"""BCOUNT: a bounded counter with replica-local escrow.

The canonical "millions of users" write-contention story (ROADMAP item
4): inventory, rate limits, and quotas are counters that must respect a
bound under concurrent writes — but coordinating every write defeats
the point of a CRDT store. The escrow construction (the numeric-
invariant design of Balegas et al., framed compositionally by
arXiv:2004.04303) splits the slack between the value and its bound into
replica-held RIGHTS that can be spent locally without coordination and
moved between replicas by a join-monotone transfer matrix:

* ``grants[rid]``   — capacity this replica added to the bound (and
                      received as inc-escrow); ``bound = Σ grants``.
* ``incs[rid]``     — this replica's lifetime increments.
* ``decs[rid]``     — this replica's lifetime decrements.
* ``xi[(f, t)]``    — inc-escrow moved f → t (lifetime total).
* ``xd[(f, t)]``    — dec-escrow moved f → t (lifetime total).

``value = Σ incs − Σ decs``. Every component is a single-writer
monotone counter (replica ``rid`` alone writes ``grants[rid]``,
``incs[rid]``, ``decs[rid]``, and row ``(rid, *)`` of each matrix), so
the join is pointwise max — commutative, associative, idempotent.

Replica-local rights derive from the state:

    inc_rights(r) = grants[r] + decs[r] − incs[r] + Σ xi[(*, r)] − Σ xi[(r, *)]
    dec_rights(r) = incs[r] − decs[r] + Σ xd[(*, r)] − Σ xd[(r, *)]

An INC spends inc-escrow and mints dec-escrow; a DEC spends dec-escrow
and mints inc-escrow; a TRANSFER debits the sender's row before the
recipient can observe the credit, so a right is never spendable twice.
Refusal (insufficient local rights) is the typed ``OUTOFBOUND`` error —
the price of coordination-freedom is that a replica may refuse while
another replica holds idle escrow. Summing the identities:

    Σ inc_rights = bound − value        Σ dec_rights = value

so rights ≥ 0 everywhere forces ``0 ≤ value ≤ bound`` — on every
replica, in every schedule of operations and deliveries. The one
delivery-order subtlety: a spend's FUNDING evidence must never lag the
spend itself, so a BCOUNT delta always ships the replica's full
per-key view (every component), making each shipped state
self-justifying under join. jmodel exhaustively explores concurrent
decrement/transfer schedules against exactly this invariant
(scripts/jmodel/world.py), and the law harness carries the
escrow-safety law beside the join laws (tests/test_lattice_laws.py).

Durability caveat (the WAL's documented bounded loss window,
docs/durability.md): the flush path ships a delta to peers before the
journal writer has necessarily made it durable. For the monotone
components a lost tail only loses un-replicated writes. For ESCROW the
window is sharper: a TRANSFER that reached peers but not disk is
forgotten by its sender on reboot, and the sender's rights appear
restored until the rejoin sync converges its own shipped matrix row
back — an escrow spend in that reboot-to-first-sync window can
double-spend the transferred right and transiently drive value below
0 cluster-wide. No fsync policy closes this today (the ship is
concurrent with the writer thread); it is the journal's documented
acknowledged-AND-flushed contract applied to escrow, narrowed to the
crashed replica's pre-heal spends. jmodel's model WAL is synchronous,
so its crash-reboot exploration covers the product's REPLAY semantics
(full-view converge), not this asynchronous window.
"""

from __future__ import annotations

# one pointwise-max join (zero-normalised) for both composed modules:
# two copies would drift independently and break cross-replica canon
from .compose import U64_MAX, _join_pmax


class BCount:
    """One bounded counter replica state (host-resident, jax-free).

    ``xi``/``xd`` must be mutated through :meth:`transfer` /
    :meth:`converge` / :meth:`from_wire` — the per-rid net-transfer
    cache that makes rights checks O(1) (instead of a full matrix scan
    per spend, the difference between ~3k and ~1M grants/sec under the
    bcount-contention bench) is maintained by exactly those entry
    points."""

    __slots__ = ("grants", "incs", "decs", "xi", "xd",
                 "_xi_net", "_xd_net")

    def __init__(self):
        self.grants: dict[int, int] = {}
        self.incs: dict[int, int] = {}
        self.decs: dict[int, int] = {}
        # (from_rid, to_rid) -> lifetime amount moved; row `from_rid`
        # is single-writer like every other component
        self.xi: dict[tuple[int, int], int] = {}
        self.xd: dict[tuple[int, int], int] = {}
        # derived: per-rid (incoming - outgoing) over each matrix
        self._xi_net: dict[int, int] = {}
        self._xd_net: dict[int, int] = {}

    def _recount(self) -> None:
        self._xi_net = {}
        self._xd_net = {}
        for (f, t), v in self.xi.items():
            self._xi_net[f] = self._xi_net.get(f, 0) - v
            self._xi_net[t] = self._xi_net.get(t, 0) + v
        for (f, t), v in self.xd.items():
            self._xd_net[f] = self._xd_net.get(f, 0) - v
            self._xd_net[t] = self._xd_net.get(t, 0) + v

    # ---- derived views -----------------------------------------------------

    def value(self) -> int:
        return sum(self.incs.values()) - sum(self.decs.values())

    def bound(self) -> int:
        return sum(self.grants.values())

    def inc_rights(self, rid: int) -> int:
        return (
            self.grants.get(rid, 0)
            + self.decs.get(rid, 0)
            - self.incs.get(rid, 0)
            + self._xi_net.get(rid, 0)
        )

    def dec_rights(self, rid: int) -> int:
        return (
            self.incs.get(rid, 0)
            - self.decs.get(rid, 0)
            + self._xd_net.get(rid, 0)
        )

    # ---- local operations (escrow-checked; False = OUTOFBOUND) ------------

    def grant(self, rid: int, amount: int) -> bool:
        """Raise the bound by ``amount``; the granting replica receives
        the matching inc-escrow. Creation is the first grant. Refuses
        (False) when the cell would pass u64: the wire decoders bound
        every span to u64 (codec _r_u64_dict), so an over-u64 cell
        would encode fine yet be refused by every peer AND make the
        origin's own journal unreplayable — the overflow must be
        stopped at the mutation, not discovered at the decoder."""
        cur = self.grants.get(rid, 0)
        if cur + amount > U64_MAX:
            return False
        self.grants[rid] = cur + amount
        return True

    def inc(self, rid: int, amount: int) -> bool:
        cur = self.incs.get(rid, 0)
        if amount > self.inc_rights(rid) or cur + amount > U64_MAX:
            return False
        self.incs[rid] = cur + amount
        return True

    def dec(self, rid: int, amount: int) -> bool:
        cur = self.decs.get(rid, 0)
        if amount > self.dec_rights(rid) or cur + amount > U64_MAX:
            return False
        self.decs[rid] = cur + amount
        return True

    def transfer(
        self, frm: int, to: int, amount: int, polarity: str = "DEC",
        unchecked: bool = False,
    ) -> bool:
        """Move ``amount`` of escrow from replica ``frm`` (the caller)
        to replica ``to``. The debit lands in the caller's OWN matrix
        row in the same mutation as the credit becomes derivable, so
        no schedule can spend a right twice. ``unchecked`` exists ONLY
        for jmodel's deliberately-broken-escrow demonstration."""
        if frm == to or amount == 0:
            return True
        src = self.xi if polarity == "INC" else self.xd
        rights = (
            self.inc_rights(frm) if polarity == "INC"
            else self.dec_rights(frm)
        )
        cur = src.get((frm, to), 0)
        if cur + amount > U64_MAX:
            return False  # matrix cells are u64 on the wire (see grant)
        if not unchecked and amount > rights:
            return False
        src[(frm, to)] = cur + amount
        net = self._xi_net if polarity == "INC" else self._xd_net
        net[frm] = net.get(frm, 0) - amount
        net[to] = net.get(to, 0) + amount
        return True

    # ---- lattice -----------------------------------------------------------

    def converge(self, other: "BCount") -> None:
        self.grants = _join_pmax(self.grants, other.grants)
        self.incs = _join_pmax(self.incs, other.incs)
        self.decs = _join_pmax(self.decs, other.decs)
        self.xi = _join_pmax(self.xi, other.xi)
        self.xd = _join_pmax(self.xd, other.xd)
        self._recount()

    def copy(self) -> "BCount":
        out = BCount()
        out.converge(self)
        return out

    def canon(self) -> tuple:
        return (
            tuple(sorted(self.grants.items())),
            tuple(sorted(self.incs.items())),
            tuple(sorted(self.decs.items())),
            tuple(sorted(self.xi.items())),
            tuple(sorted(self.xd.items())),
        )

    def is_bottom(self) -> bool:
        return not (
            self.grants or self.incs or self.decs or self.xi or self.xd
        )

    # ---- wire shape --------------------------------------------------------
    # delta/BCOUNT ships the FULL per-key view as five components (see
    # module docstring on self-justifying states): three {rid: u64}
    # spans plus two transfer matrices as (from, to, amount) triples.

    def to_wire(self) -> tuple:
        return (
            dict(self.grants), dict(self.incs), dict(self.decs),
            dict(self.xi), dict(self.xd),
        )

    @classmethod
    def from_wire(cls, wire: tuple) -> "BCount":
        grants, incs, decs, xi, xd = wire
        out = cls()
        # zero-normalised like the join: wire spans may carry zeros
        out.grants = {k: v for k, v in grants.items() if v}
        out.incs = {k: v for k, v in incs.items() if v}
        out.decs = {k: v for k, v in decs.items() if v}
        out.xi = {k: v for k, v in xi.items() if v}
        out.xd = {k: v for k, v in xd.items() if v}
        out._recount()
        return out
