"""TLOG: timestamped log with grow-only cutoff as batched TPU kernels.

Semantics (docs/_docs/types/tlog.md:116-133): a log is a list of
(value, ts) entries sorted ts-desc (value-desc on ties); merging unions the
lists, drops duplicates (equal ts AND equal value), takes the max cutoff,
and discards entries with ts < cutoff. Reference repo:
jylis/repo_tlog.pony:29-111 (INS/GET/SIZE/CUTOFF/TRIM/TRIMAT/CLR).

TPU-native layout — the keyspace lives as the SORT PLANES themselves.
Each entry packs into u32 planes whose ascending lexicographic order is
exactly the canonical device order (valid first, ts desc, vid desc):

  ``nth[key, slot]`` : ~ts >> 32   (wide layout only)
  ``ntl[key, slot]`` : ~ts & 0xFFFFFFFF
  ``nv [key, slot]`` : ~(vid + 1)  (the empty slot's vid = -1 becomes the
                                    all-ones PAD, so invalid entries ARE
                                    the maximal key — no validity operand)

plus ``length[key] : int32`` and ``cutoff[key] : uint64``. Storing planes
rather than u64 values means a merge is ONE stable multi-key ``lax.sort``
over the concatenated rows with zero encode/decode traffic; only the
once-per-batch delta rows (narrow) pay the u64-to-plane conversion.

The layout is adaptive (the ops/ujson_device pattern): while every ts in
a keyspace fits u32 — logical client timestamps usually do — ``nth`` is
the constant 0xFFFFFFFF and is NOT STORED (``state.nth is None``); merges
sort TWO planes instead of three. The first 64-bit ts upgrades the state
losslessly by materialising the constant plane (``widen``); the host repo
triggers it before draining wide data. Clients never see the difference:
host GET re-sorts the requested row with full strings, and TRIM's cutoff
is the ts at a given index, which only depends on the ts multiset — which
is also why the vid tie-break (replacing round-2's 8-byte value-prefix
rank planes) is exact.

Duplicates leave holes after the merge sort, so the compaction sort runs
under a batch-level ``lax.cond``: the common dup-free batch skips it, and
re-delivered batches (all dups) pay it once. Versus the round-2 7-operand
two-sort kernel the narrow layout measures ~3.5x on the 10k-key x
1k-entry benchmark.

Contract: one converge batch has at most one delta per key (deltas
coalesce per key per flush window, as in the reference repo pattern), and
interner ids stay below 2**31 (ops/interner.py enforces this) so the
biased vid always fits its u32 plane.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

UINT64 = jnp.uint64
INT64 = jnp.int64
U32 = jnp.uint32

_PAD32 = jnp.uint32(0xFFFFFFFF)

# largest ts representable in the narrow (2-plane) layout; CLR needs
# latest+1 to fit too, hence the -1
TS32_MAX = 0xFFFFFFFF - 1

# trim counts at or above this sentinel are no-ops; the host uses it to pad
# trim batches and to mix no-trim rows into fused drain+trim dispatches
TRIM_NOOP = 1 << 62


class TLogState(NamedTuple):
    nth: Optional[jax.Array]  # (K, L) u32 ~ts_hi, or None in narrow layout
    ntl: jax.Array  # (K, L) u32 ~ts_lo
    nv: jax.Array  # (K, L) u32 ~(vid+1); 0xFFFFFFFF in empty slots
    length: jax.Array  # (K,) int32 valid-entry count
    cutoff: jax.Array  # (K,) uint64 grow-only cutoff timestamp

    @property
    def wide(self) -> bool:
        return self.nth is not None

    @property
    def shape(self):
        return self.ntl.shape


def init(num_keys: int, max_len: int, wide: bool = False) -> TLogState:
    pad = jnp.full((num_keys, max_len), _PAD32, U32)
    return TLogState(
        pad if wide else None,
        pad,
        pad,
        jnp.zeros((num_keys,), jnp.int32),
        jnp.zeros((num_keys,), UINT64),
    )


def widen(state: TLogState) -> TLogState:
    """Narrow -> wide, losslessly: in the narrow layout every stored ts
    fits u32, so the missing ~ts_hi plane is the constant 0xFFFFFFFF for
    real entries — which equals the PAD value, so the whole plane is
    constant."""
    if state.wide:
        return state
    return state._replace(nth=jnp.full(state.shape, _PAD32, U32))


def _split_neg64(x):
    """u64 -> (~hi, ~lo) u32 planes: ascending lex order over the pair is
    DESCENDING u64 order, with every compare native u32 (the TPU has no
    64-bit datapath; sorting emulated-u64 keys measured ~4x slower)."""
    nx = ~x
    return (nx >> jnp.uint64(32)).astype(U32), nx.astype(U32)


def _join_neg64(nhi, nlo):
    return ~((nhi.astype(UINT64) << jnp.uint64(32)) | nlo.astype(UINT64))


def _delta_planes(d_ts, d_vid, valid, wide: bool):
    """Delta rows (u64 ts, i64 vid) -> sort planes; invalid slots become
    PAD. Narrow layouts assume (host guarantees) every valid ts < 2**32."""
    nth, ntl = _split_neg64(d_ts)
    nv = ~(d_vid.astype(U32) + U32(1))  # -1 -> PAD, v -> ~(v+1)
    out = (
        jnp.where(valid, ntl, _PAD32),
        jnp.where(valid, nv, _PAD32),
    )
    return ((jnp.where(valid, nth, _PAD32),) + out) if wide else out


def _ts_ge(planes, cut_hi, cut_lo, wide: bool):
    """ts >= cut per slot, computed in negated-plane space: lex
    (nth, ntl) <= (~cut_hi, ~cut_lo)."""
    if wide:
        nth, ntl = planes[0], planes[1]
        return (nth < cut_hi[:, None]) | (
            (nth == cut_hi[:, None]) & (ntl <= cut_lo[:, None])
        )
    return planes[0] <= cut_lo[:, None]


def _decode_vid(nv):
    """nv plane -> int64 vid (-1 for PAD); exact for vids < 2**31."""
    return (~nv).astype(jnp.int32).astype(INT64) - 1


def _decode_ts(state_planes, wide: bool):
    if wide:
        return _join_neg64(state_planes[0], state_planes[1])
    return (~state_planes[0]).astype(UINT64)


def _assemble(a_planes, a_cut, d_ts, d_vid, d_cut, wide: bool, tail: bool):
    """Combine state plane rows with delta rows under the joined cutoff.
    tail=True writes the delta into the rows' trailing Ld columns (the
    dense in-place path — the caller flags rows whose entries reach into
    that tail as overflow, so only PAD is ever overwritten); tail=False
    concatenates to width L + Ld. Returns (planes, cutoff)."""
    cut = jnp.maximum(a_cut, d_cut)
    nch, ncl = _split_neg64(cut)

    # state rows stay sorted under the raised cutoff, but entries below it
    # must die: re-filter to PAD (skipped entirely when no cutoff rose)
    def _refilter(planes):
        ok = _ts_ge(planes, nch, ncl, wide) & (planes[-1] != _PAD32)
        return tuple(jnp.where(ok, p, _PAD32) for p in planes)

    a_planes = lax.cond(
        jnp.any(cut > a_cut), _refilter, lambda p: p, a_planes
    )
    d_valid = (d_vid >= 0) & (d_ts >= cut[:, None])
    d_planes = _delta_planes(d_ts, d_vid, d_valid, wide)
    if tail:
        Ld = d_ts.shape[1]
        planes = tuple(
            a.at[:, a.shape[1] - Ld :].set(d)
            for a, d in zip(a_planes, d_planes)
        )
    else:
        planes = tuple(
            jnp.concatenate([a, d], axis=1)
            for a, d in zip(a_planes, d_planes)
        )
    return planes, cut


def _merge_planes(planes, wide: bool):
    """The merge core: one stable multi-key sort, neighbor dedup, and a
    batch-level conditional compaction sort (dup-free batches skip it).
    Returns (planes, length)."""
    nk = len(planes)
    planes = lax.sort(planes, dimension=1, is_stable=True, num_keys=nk)
    real = planes[-1] != _PAD32
    # duplicates (equal ts AND value; vid equality IS value equality) are
    # now adjacent — drop every entry equal to its left neighbor
    eq = real[:, 1:]
    for p in planes:
        eq = eq & (p[:, 1:] == p[:, :-1])
    dup = jnp.zeros(real.shape, bool).at[:, 1:].set(eq)
    keep = real & ~dup
    length = jnp.sum(keep, axis=1).astype(jnp.int32)

    def _with_compact(pl):
        return lax.sort(
            tuple(jnp.where(keep, p, _PAD32) for p in pl),
            dimension=1,
            is_stable=True,
            num_keys=nk,
        )

    planes = lax.cond(jnp.any(dup), _with_compact, lambda p: p, planes)
    # scrub the tail so converged states are bitwise equal for equal
    # logical content (dup-free path leaves only PADs past length anyway)
    m = jnp.arange(real.shape[1])[None, :] < length[:, None]
    planes = tuple(jnp.where(m, p, _PAD32) for p in planes)
    return planes, length


def _state_planes(state: TLogState):
    if state.wide:
        return (state.nth, state.ntl, state.nv)
    return (state.ntl, state.nv)


def _rebuild(state: TLogState, planes, length, cutoff) -> TLogState:
    if state.wide:
        return TLogState(planes[0], planes[1], planes[2], length, cutoff)
    return TLogState(None, planes[0], planes[1], length, cutoff)


def converge_batch(
    state: TLogState,
    key_idx: Optional[jax.Array],
    d_ts: jax.Array,
    d_vid: jax.Array,
    d_cutoff: jax.Array,
) -> tuple[TLogState, jax.Array]:
    """Join delta logs into the keyspace (unique keys per batch).

    key_idx: (B,) rows, or None for the DENSE path — delta rows aligned
    1:1 with the whole keyspace, no gather/scatter (full-keyspace
    anti-entropy drains; the repo_counters dense-drain pattern).
    d_ts/d_vid: (B, Ld) padded delta rows; d_cutoff: (B,).

    Returns (state, overflow) where overflow (B,) bool flags rows that
    could not absorb the merge at capacity L (sparse: merged length
    exceeded L and the row was truncated; dense: the row's entries reach
    into the tail columns the delta writes through). Either way, on
    overflow the caller must discard the returned state, grow() the
    retained PRE-merge state, and re-merge the delta into that. The host
    repo checks lengths up front to make this path rare. Narrow-layout
    callers guarantee every delta ts <= TS32_MAX (the repo widens first).
    """
    L = state.shape[1]
    sp = _state_planes(state)
    if key_idx is None:
        # dense in-place: the delta lands in the rows' trailing PAD
        # columns and the sort stays at width L — no gather/scatter, no
        # concat, no slice-back. Rows long enough for their entries to
        # reach the tail are flagged (conservatively) for the grow-retry.
        Ld = d_ts.shape[1]
        overflow = state.length > (L - Ld)
        planes, m_cut = _assemble(
            sp, state.cutoff, d_ts, d_vid, d_cutoff, state.wide, tail=True
        )
        planes, m_len = _merge_planes(planes, state.wide)
        return _rebuild(state, planes, m_len, m_cut), overflow
    a_planes = tuple(p[key_idx] for p in sp)
    a_cut = state.cutoff[key_idx]
    m_planes, m_cut = _assemble(
        a_planes, a_cut, d_ts, d_vid, d_cutoff, state.wide, tail=False
    )
    m_planes, m_len = _merge_planes(m_planes, state.wide)
    overflow = m_len > L
    planes = tuple(
        s.at[key_idx].set(p[:, :L], mode="drop")
        for s, p in zip(sp, m_planes)
    )
    return (
        _rebuild(
            state,
            planes,
            state.length.at[key_idx].set(jnp.minimum(m_len, L), mode="drop"),
            state.cutoff.at[key_idx].set(m_cut, mode="drop"),
        ),
        overflow,
    )


def insert_batch(
    state: TLogState,
    key_idx: jax.Array,
    ts: jax.Array,
    vid: jax.Array,
) -> tuple[TLogState, jax.Array]:
    """Local INS of one entry per key (unique keys): a 1-entry log join."""
    return converge_batch(
        state,
        key_idx,
        ts[:, None],
        vid[:, None],
        jnp.zeros(key_idx.shape, UINT64),
    )


def _apply_cutoff_rows(planes, new_cut, wide: bool):
    """Drop each row's suffix with ts < new_cut (rows are canonical)."""
    nch, ncl = _split_neg64(new_cut)
    keepmask = _ts_ge(planes, nch, ncl, wide) & (planes[-1] != _PAD32)
    keep = jnp.sum(keepmask, axis=1).astype(jnp.int32)
    m = jnp.arange(planes[0].shape[1])[None, :] < keep[:, None]
    return tuple(jnp.where(m, p, _PAD32) for p in planes), keep


def trimat_batch(state: TLogState, key_idx: jax.Array, t: jax.Array) -> TLogState:
    """TRIMAT: raise each key's cutoff to max(cutoff, t) and drop older
    entries (tlog.md:46-52)."""
    new_cut = jnp.maximum(state.cutoff[key_idx], t)
    sp = _state_planes(state)
    rows = tuple(p[key_idx] for p in sp)
    r_planes, r_len = _apply_cutoff_rows(rows, new_cut, state.wide)
    planes = tuple(
        s.at[key_idx].set(p, mode="drop") for s, p in zip(sp, r_planes)
    )
    return _rebuild(
        state,
        planes,
        state.length.at[key_idx].set(r_len, mode="drop"),
        state.cutoff.at[key_idx].set(new_cut, mode="drop"),
    )


def trim_batch(state: TLogState, key_idx: jax.Array, count: jax.Array) -> TLogState:
    """TRIM: cutoff := ts of entry at index count-1 (tlog.md:54-60);
    count 0 == CLR; count > length is a no-op; count < 0 is a no-op (the
    reference parses count as unsigned, so negatives never occur there)."""
    sp = _state_planes(state)
    length = state.length[key_idx]
    L = state.shape[1]
    at = jnp.clip(count - 1, 0, L - 1)[:, None]
    if state.wide:
        hi_at = jnp.take_along_axis(sp[0][key_idx], at, axis=1)[:, 0]
        lo_at = jnp.take_along_axis(sp[1][key_idx], at, axis=1)[:, 0]
        ts_at = _join_neg64(hi_at, lo_at)
        hi0 = sp[0][key_idx][:, 0]
        lo0 = sp[1][key_idx][:, 0]
        latest = _join_neg64(hi0, lo0)
    else:
        ts_at = (~jnp.take_along_axis(sp[0][key_idx], at, axis=1)[:, 0]).astype(UINT64)
        latest = (~sp[0][key_idx][:, 0]).astype(UINT64)
    latest_plus1 = jnp.where(length > 0, latest + 1, 0)  # CLR target
    target = jnp.where(
        count == 0,
        latest_plus1,
        jnp.where((count > 0) & (count <= length), ts_at, 0),
    )
    return trimat_batch(state, key_idx, target)


def converge_then_trim(
    state: TLogState,
    key_idx: Optional[jax.Array],
    d_ts: jax.Array,
    d_vid: jax.Array,
    d_cutoff: jax.Array,
    trim_idx: jax.Array,
    counts: jax.Array,
) -> tuple[TLogState, jax.Array]:
    """Fused drain + TRIM/CLR: one dispatch where the repo previously paid
    two sequential ~100 ms tunneled launches (VERDICT r2 weak item 6). The
    trim reads the freshly merged rows; counts >= TRIM_NOOP are no-ops, so
    pure drains and pure trims are the same kernel."""
    st, overflow = converge_batch(state, key_idx, d_ts, d_vid, d_cutoff)
    return trim_batch(st, trim_idx, counts), overflow


def clear_batch(state: TLogState, key_idx: jax.Array) -> TLogState:
    """CLR: cutoff := latest ts + 1; no-op on empty logs (tlog.md:62-66)."""
    return trim_batch(state, key_idx, jnp.zeros(key_idx.shape, jnp.int64))


def read_row(state: TLogState, key: jax.Array):
    """GET: one key's padded row decoded to (ts, vid, length) — host
    renders & sorts with full strings."""
    sp = _state_planes(state)
    row = tuple(p[key] for p in sp)
    if state.wide:
        ts = _join_neg64(row[0], row[1])
    else:
        ts = (~row[0]).astype(UINT64)
    return ts, _decode_vid(row[-1]), state.length[key]


def decode_ts_np(nth, ntl):
    """Host-side plane decode to u64 ts; nth is None for narrow states."""
    low = (~np.asarray(ntl, dtype=np.uint32)).astype(np.uint64)
    if nth is None:
        return low
    hi = (~np.asarray(nth, dtype=np.uint32)).astype(np.uint64)
    return (hi << np.uint64(32)) | low


def decode_vid_np(nv):
    """Host-side nv plane -> int64 vids (-1 for empty slots); exact for
    vids < 2**31 (interner-enforced)."""
    return (~np.asarray(nv, dtype=np.uint32)).astype(np.int64) - 1


def encode_vid_np(vid):
    """Host-side int64 vids -> nv plane (-1 maps to PAD)."""
    return ~(np.asarray(vid, np.int64).astype(np.uint32) + np.uint32(1))


def grow(state: TLogState, num_keys: int, max_len: int) -> TLogState:
    k, l = state.shape
    if (num_keys, max_len) == (k, l):
        return state
    pad = jnp.full((num_keys, max_len), _PAD32, U32)
    planes = tuple(
        pad.at[:k, :l].set(p) for p in _state_planes(state)
    )
    return _rebuild(
        state,
        planes,
        jnp.zeros((num_keys,), jnp.int32).at[:k].set(state.length),
        jnp.zeros((num_keys,), UINT64).at[:k].set(state.cutoff),
    )
