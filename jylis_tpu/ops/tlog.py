"""TLOG: timestamped log with grow-only cutoff as batched TPU kernels.

Semantics (docs/_docs/types/tlog.md:116-133): a log is a list of
(value, ts) entries sorted ts-desc (value-desc on ties); merging unions the
lists, drops duplicates (equal ts AND equal value), takes the max cutoff,
and discards entries with ts < cutoff. Reference repo:
jylis/repo_tlog.pony:29-111 (INS/GET/SIZE/CUTOFF/TRIM/TRIMAT/CLR).

TPU-native layout: the keyspace is a padded 2-D block —
``ts[key, slot] : uint64``, ``vid[key, slot] : int64`` (interned value id,
-1 = empty slot), ``rank[key, slot] : uint64`` (order-preserving value
prefix), plus ``length[key] : int32`` and ``cutoff[key] : uint64``. Rows are
kept in canonical device order: valid entries first, sorted by
(ts desc, rank desc, vid desc). vid is a deterministic final tie-break so
replicas converge to identical tensors; host GET rendering re-sorts the one
requested row with full strings, so client-visible ordering is exactly the
documented string order even on rank-prefix collisions.

The merge is a vmap'd sort-dedup-mask kernel: concat both rows, two stable
multi-key ``lax.sort`` passes (order, then compaction), neighbor-equality
dedup — O(L log L) in parallel on device versus the reference's sequential
per-entry list insertion.

Contract: one converge batch has at most one delta per key (deltas coalesce
per key per flush window, as in the reference repo pattern).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

UINT64 = jnp.uint64
INT64 = jnp.int64


class TLogState(NamedTuple):
    ts: jax.Array  # (K, L) uint64, 0 in empty slots
    rank: jax.Array  # (K, L) uint64, 0 in empty slots
    vid: jax.Array  # (K, L) int64, -1 in empty slots
    length: jax.Array  # (K,) int32 valid-entry count
    cutoff: jax.Array  # (K,) uint64 grow-only cutoff timestamp


def init(num_keys: int, max_len: int) -> TLogState:
    return TLogState(
        jnp.zeros((num_keys, max_len), UINT64),
        jnp.zeros((num_keys, max_len), UINT64),
        jnp.full((num_keys, max_len), -1, INT64),
        jnp.zeros((num_keys,), jnp.int32),
        jnp.zeros((num_keys,), UINT64),
    )


U32 = jnp.uint32


def _split_neg64(x):
    """u64 -> (~hi, ~lo) u32 planes: ascending lex order over the pair is
    DESCENDING u64 order, with every compare native u32 (the TPU has no
    64-bit datapath; sorting emulated-u64 keys measured ~4x slower)."""
    nx = ~x
    return (nx >> jnp.uint64(32)).astype(U32), nx.astype(U32)


def _join_neg64(nhi, nlo):
    return ~((nhi.astype(UINT64) << jnp.uint64(32)) | nlo.astype(UINT64))


def _scrub(ts, rank, vid, length):
    """Reset slots past `length` to the padding identity so converged
    states are bitwise equal across replicas."""
    keep = jnp.arange(ts.shape[0]) < length
    return (
        jnp.where(keep, ts, 0),
        jnp.where(keep, rank, 0),
        jnp.where(keep, vid, -1),
        length,
    )


def _canonicalize(ts, rank, vid, valid):
    """Stable-sort one row to canonical order: valid entries first, then
    (ts desc, rank desc, vid desc). Returns (ts, rank, vid, length).

    All seven u32 sort operands are keys — the split planes double as the
    payload, so nothing extra moves and every comparison is a native u32
    op. The trailing vid keys only refine the order beyond the previous
    4-key form (vid was already the final tie-break)."""
    inv = (~valid).astype(U32)
    nth, ntl = _split_neg64(ts)
    nrh, nrl = _split_neg64(rank)
    nvh, nvl = _split_neg64(vid.astype(UINT64))
    inv, nth, ntl, nrh, nrl, nvh, nvl = lax.sort(
        (inv, nth, ntl, nrh, nrl, nvh, nvl),
        dimension=0,
        is_stable=True,
        num_keys=7,
    )
    return _scrub(
        _join_neg64(nth, ntl),
        _join_neg64(nrh, nrl),
        _join_neg64(nvh, nvl).astype(INT64),
        jnp.sum(valid).astype(jnp.int32),
    )


def _compact(ts, rank, vid, keep):
    """Stable compaction of an already-ordered row: push ~keep entries to
    the tail (single u32 sort key, order among kept entries preserved).

    Measured alternative: a cumsum-position + scatter partition (O(n) in
    compares) ran ~70x SLOWER than this sort on the v5e — vmap'd
    computed-index scatters do not vectorise; the sort network does."""
    inv = (~keep).astype(U32)
    nth, ntl = _split_neg64(ts)
    nrh, nrl = _split_neg64(rank)
    nvh, nvl = _split_neg64(vid.astype(UINT64))
    inv, nth, ntl, nrh, nrl, nvh, nvl = lax.sort(
        (inv, nth, ntl, nrh, nrl, nvh, nvl),
        dimension=0,
        is_stable=True,
        num_keys=1,
    )
    return _scrub(
        _join_neg64(nth, ntl),
        _join_neg64(nrh, nrl),
        _join_neg64(nvh, nvl).astype(INT64),
        jnp.sum(keep).astype(jnp.int32),
    )


def _merge_row(a_ts, a_rank, a_vid, a_cut, b_ts, b_rank, b_vid, b_cut):
    """Join two padded rows -> (ts, rank, vid, length, cutoff) of size
    len(a)+len(b) (caller truncates; see converge_batch overflow contract)."""
    ts = jnp.concatenate([a_ts, b_ts])
    rank = jnp.concatenate([a_rank, b_rank])
    vid = jnp.concatenate([a_vid, b_vid])
    cut = jnp.maximum(a_cut, b_cut)
    valid = (vid >= 0) & (ts >= cut)
    ts, rank, vid, _ = _canonicalize(ts, rank, vid, valid)
    # duplicates (equal ts AND value; vid equality IS value equality) are now
    # adjacent — drop every entry equal to its left neighbor
    dup = jnp.zeros(ts.shape, bool).at[1:].set(
        (ts[1:] == ts[:-1]) & (vid[1:] == vid[:-1]) & (vid[1:] >= 0)
    )
    ts, rank, vid, length = _compact(ts, rank, vid, (vid >= 0) & ~dup)
    return ts, rank, vid, length, cut


def converge_batch(
    state: TLogState,
    key_idx: jax.Array,
    d_ts: jax.Array,
    d_rank: jax.Array,
    d_vid: jax.Array,
    d_cutoff: jax.Array,
) -> tuple[TLogState, jax.Array]:
    """Join delta logs into the keyspace (unique keys per batch).

    key_idx: (B,); d_ts/d_rank/d_vid: (B, Ld) padded delta rows; d_cutoff:
    (B,). Returns (state, overflow) where overflow (B,) bool flags rows whose
    merged length exceeded capacity L. Overflowed rows in the RETURNED state
    are truncated (lowest-(ts,value) entries dropped); on overflow the caller
    must discard the returned state, grow() the retained PRE-merge state, and
    re-merge the delta into that. The host repo checks lengths up front to
    make this path rare.
    """
    L = state.ts.shape[1]
    a_ts = state.ts[key_idx]
    a_rank = state.rank[key_idx]
    a_vid = state.vid[key_idx]
    a_cut = state.cutoff[key_idx]
    m_ts, m_rank, m_vid, m_len, m_cut = jax.vmap(_merge_row)(
        a_ts, a_rank, a_vid, a_cut, d_ts, d_rank, d_vid, d_cutoff
    )
    overflow = m_len > L
    return (
        TLogState(
            state.ts.at[key_idx].set(m_ts[:, :L], mode="drop"),
            state.rank.at[key_idx].set(m_rank[:, :L], mode="drop"),
            state.vid.at[key_idx].set(m_vid[:, :L], mode="drop"),
            state.length.at[key_idx].set(jnp.minimum(m_len, L), mode="drop"),
            state.cutoff.at[key_idx].set(m_cut, mode="drop"),
        ),
        overflow,
    )


def insert_batch(
    state: TLogState,
    key_idx: jax.Array,
    ts: jax.Array,
    rank: jax.Array,
    vid: jax.Array,
) -> tuple[TLogState, jax.Array]:
    """Local INS of one entry per key (unique keys): a 1-entry log join."""
    return converge_batch(
        state,
        key_idx,
        ts[:, None],
        rank[:, None],
        vid[:, None],
        jnp.zeros(key_idx.shape, UINT64),
    )


def _row_apply_cutoff(ts, rank, vid, length, new_cut):
    """Drop the suffix with ts < new_cut from a canonical-order row."""
    keep = jnp.sum((ts >= new_cut) & (vid >= 0)).astype(jnp.int32)
    idx = jnp.arange(ts.shape[0])
    m = idx < keep
    return jnp.where(m, ts, 0), jnp.where(m, rank, 0), jnp.where(m, vid, -1), keep


def trimat_batch(state: TLogState, key_idx: jax.Array, t: jax.Array) -> TLogState:
    """TRIMAT: raise each key's cutoff to max(cutoff, t) and drop older
    entries (tlog.md:46-52)."""
    new_cut = jnp.maximum(state.cutoff[key_idx], t)
    r_ts, r_rank, r_vid, r_len = jax.vmap(_row_apply_cutoff)(
        state.ts[key_idx],
        state.rank[key_idx],
        state.vid[key_idx],
        state.length[key_idx],
        new_cut,
    )
    return TLogState(
        state.ts.at[key_idx].set(r_ts, mode="drop"),
        state.rank.at[key_idx].set(r_rank, mode="drop"),
        state.vid.at[key_idx].set(r_vid, mode="drop"),
        state.length.at[key_idx].set(r_len, mode="drop"),
        state.cutoff.at[key_idx].set(new_cut, mode="drop"),
    )


def trim_batch(state: TLogState, key_idx: jax.Array, count: jax.Array) -> TLogState:
    """TRIM: cutoff := ts of entry at index count-1 (tlog.md:54-60);
    count 0 == CLR; count > length is a no-op; count < 0 is a no-op (the
    reference parses count as unsigned, so negatives never occur there)."""
    rows_ts = state.ts[key_idx]  # (B, L)
    length = state.length[key_idx]
    L = rows_ts.shape[1]
    at = jnp.clip(count - 1, 0, L - 1)
    ts_at = jnp.take_along_axis(rows_ts, at[:, None], axis=1)[:, 0]
    latest_plus1 = jnp.where(length > 0, rows_ts[:, 0] + 1, 0)  # CLR target
    target = jnp.where(
        count == 0,
        latest_plus1,
        jnp.where((count > 0) & (count <= length), ts_at, 0),
    )
    return trimat_batch(state, key_idx, target)


def clear_batch(state: TLogState, key_idx: jax.Array) -> TLogState:
    """CLR: cutoff := latest ts + 1; no-op on empty logs (tlog.md:62-66)."""
    return trim_batch(state, key_idx, jnp.zeros(key_idx.shape, jnp.int64))


def read_row(state: TLogState, key: jax.Array):
    """GET: one key's padded row (ts, vid, length) — host renders & sorts
    with full strings."""
    return state.ts[key], state.vid[key], state.length[key]


def grow(state: TLogState, num_keys: int, max_len: int) -> TLogState:
    k, l = state.ts.shape
    if (num_keys, max_len) == (k, l):
        return state
    return TLogState(
        jnp.zeros((num_keys, max_len), UINT64).at[:k, :l].set(state.ts),
        jnp.zeros((num_keys, max_len), UINT64).at[:k, :l].set(state.rank),
        jnp.full((num_keys, max_len), -1, INT64).at[:k, :l].set(state.vid),
        jnp.zeros((num_keys,), jnp.int32).at[:k].set(state.length),
        jnp.zeros((num_keys,), UINT64).at[:k].set(state.cutoff),
    )
