"""Composed CRDTs: the inner-lattice registry and the generic MAP.

ROADMAP item 4 — the five flat types (plus TENSOR) are ports;
composition is the creative step the paper's design leaves open.
"Composing and Decomposing Op-Based CRDTs with Semidirect Products"
(arXiv:2004.04303) gives the frame: a key→lattice map whose join is
the product of per-field joins, and "Big(ger) Sets" (arXiv:1605.06424)
the replication discipline: DECOMPOSED per-field deltas, so one field
edit never ships the map — the property that lets a composite type
ride the delta-interval / Merkle-range ladder (schema v8) unchanged.

Two layers live here:

* **The inner-lattice registry** (:data:`REGISTRY`): every value type
  a MAP field can hold, described over its WIRE-delta representation
  (the exact shapes cluster/codec.py ships for the flat types — a dict
  for GCOUNT, a ``(value, ts)`` pair for TREG, …), with join / canon /
  bottom / RESP write+render hooks and a seeded generator for the
  pass-8 law harness. tests/test_lattice_laws.py iterates this
  registry to auto-generate MAP join laws per registered inner type —
  registering a new lattice buys its law coverage for free.

* **The MAP field lattice** (:class:`MapCRDT` holding
  :class:`Field` s): each field is a PRODUCT lattice
  ``(itype, ver, tomb, val)`` — per-replica edit counters (``ver``,
  pointwise max), a per-field causal-context tombstone (``tomb``,
  pointwise max), and the inner value (inner join). A field is LIVE
  iff some edit is not covered by the tombstone (observed-remove at
  field granularity: a DEL only covers the edits its replica had
  seen, so a concurrent SET survives — add-wins). Removal HIDES; the
  inner content is retained and keeps joining under the tombstone, so
  the product stays a true join-semilattice (content-GC on death is
  exactly the shortcut that breaks associativity: a resurrecting edit
  would see different content depending on join order). Conflicting
  inner types on one field resolve by type-name dominance (the
  lexicographically greater name wins wholesale) — a deterministic
  rank so the composite is still a lattice under misconfiguration.

Field deltas pack the composite ``(key, field)`` into ONE opaque wire
key (:func:`pack_field`), so the whole existing (key, delta) batch
machinery — journal frames, delta-interval retransmission, the
per-type 256-leaf digest tree, budgeted range pulls — operates at
FIELD granularity with zero changes: digest leaves hash (key, field)
pairs and range repair pulls divergent fields, not whole maps.
"""

from __future__ import annotations

U64_MAX = (1 << 64) - 1


def _norm(d: dict) -> dict:
    """Drop zero entries: a zero counter/tombstone cell is the SAME
    lattice point as an absent one, and must canon/join identically
    (wire decodes may legally carry explicit zeros)."""
    return {k: v for k, v in d.items() if v}


def _join_pmax(a: dict, b: dict) -> dict:
    """Pointwise-max join of {int: int} maps (the G-Counter core),
    zero-normalised."""
    out = _norm(a)
    for k, v in b.items():
        if v > out.get(k, 0):
            out[k] = v
    return out


# ---- inner lattices over their wire-delta representations ------------------


class InnerLattice:
    """One registry row: a value lattice a MAP field can hold, expressed
    over the wire-delta shape cluster/codec.py already ships for the
    flat type of the same name. ``join(a, b)`` returns a NEW value
    (inputs unaliased); ``canon`` is the representation-normal
    comparable/digestible form; ``bottom()`` is the join identity (the
    branch-free wire unit encodes it instead of a presence flag);
    ``write(cur, rid, args)`` parses a ``MAP <TYPE> SET key field
    <args…>`` tail into the delta to join AND ship (raises ValueError
    on a malformed tail); ``render(resp, v)`` answers a GET; ``gen``
    drives the generated law harness."""

    __slots__ = ()
    name: str = "?"

    def bottom(self):
        raise NotImplementedError

    def join(self, a, b):
        raise NotImplementedError

    def copy(self, v):
        raise NotImplementedError

    def canon(self, v) -> tuple:
        raise NotImplementedError

    def is_bottom(self, v) -> bool:
        return self.canon(v) == self.canon(self.bottom())

    def write(self, cur, rid: int, args: list):
        raise NotImplementedError

    def render(self, resp, v) -> None:
        raise NotImplementedError

    def gen(self, rng):
        raise NotImplementedError

    # parse helper shared by the write hooks: strict u64 (models/base
    # duplicates this over ParseError; here ValueError keeps ops/ free
    # of the models import)
    @staticmethod
    def _u64(b: bytes) -> int:
        if not b.isdigit():
            raise ValueError("not a u64")
        v = int(b)
        if v > U64_MAX:
            raise ValueError("u64 overflow")
        return v


class InnerTREG(InnerLattice):
    """LWW pair (value: bytes, ts: u64); join = max by (ts, value) —
    hostref.TReg's exact rule. Bottom (b"", 0) equals a written empty
    pair at ts 0, the reference's documented unset behaviour."""

    name = "TREG"

    def bottom(self):
        return (b"", 0)

    def join(self, a, b):
        return a if (a[1], a[0]) >= (b[1], b[0]) else b

    def copy(self, v):
        return v  # immutable tuple

    def canon(self, v) -> tuple:
        return (v[1], v[0])

    def write(self, cur, rid: int, args: list):
        if len(args) != 2:
            raise ValueError("TREG write takes: value timestamp")
        return (args[0], self._u64(args[1]))

    def render(self, resp, v) -> None:
        value, ts = v
        resp.array_start(2)
        resp.string(value)
        resp.u64(ts)

    def gen(self, rng):
        if rng.random() < 0.15:
            return self.bottom()
        return (
            bytes(rng.choices(b"abcdef", k=rng.randint(0, 4))),
            rng.randint(0, 5),
        )


class InnerTLOG(InnerLattice):
    """(entries: [(value, ts)] ts-desc, cutoff: u64); join = entry union
    above the max cutoff — hostref.TLog's exact rule."""

    name = "TLOG"

    def bottom(self):
        return ((), 0)

    def join(self, a, b):
        cutoff = max(a[1], b[1])
        merged = set(a[0]) | set(b[0])
        entries = tuple(
            sorted(
                (e for e in merged if e[1] >= cutoff),
                key=lambda e: (e[1], e[0]),
                reverse=True,
            )
        )
        return (entries, cutoff)

    def copy(self, v):
        return (tuple(v[0]), v[1])

    def canon(self, v) -> tuple:
        return (tuple(v[0]), v[1])

    def write(self, cur, rid: int, args: list):
        if len(args) != 2:
            raise ValueError("TLOG write takes: value timestamp")
        return (((args[0], self._u64(args[1])),), 0)

    def render(self, resp, v) -> None:
        entries, _cutoff = v
        resp.array_start(len(entries))
        for value, ts in entries:
            resp.array_start(2)
            resp.string(value)
            resp.u64(ts)

    def gen(self, rng):
        entries = tuple(
            (bytes(rng.choices(b"xyz", k=rng.randint(1, 3))),
             rng.randint(0, 9))
            for _ in range(rng.randint(0, 4))
        )
        cutoff = rng.randint(0, 9) if rng.random() < 0.3 else 0
        return self.join((entries, 0), ((), cutoff))


class InnerGCOUNT(InnerLattice):
    """{rid: u64}; join = pointwise max; value = wrapping sum."""

    name = "GCOUNT"

    def bottom(self):
        return {}

    def join(self, a, b):
        return _join_pmax(a, b)

    def copy(self, v):
        return dict(v)

    def canon(self, v) -> tuple:
        return tuple(sorted(v.items()))

    def write(self, cur, rid: int, args: list):
        if len(args) != 1:
            raise ValueError("GCOUNT write takes: amount")
        amount = self._u64(args[0])
        cur = cur if cur is not None else {}
        return {rid: (cur.get(rid, 0) + amount) & U64_MAX}

    def render(self, resp, v) -> None:
        resp.u64(sum(v.values()) & U64_MAX)

    def gen(self, rng):
        return {
            rid: rng.randint(1, 1 << 40)
            for rid in rng.sample(range(1, 9), rng.randint(0, 4))
        }


class InnerPNCOUNT(InnerLattice):
    """({rid: u64}, {rid: u64}); value = P − N signed-64 modular."""

    name = "PNCOUNT"

    def bottom(self):
        return ({}, {})

    def join(self, a, b):
        return (_join_pmax(a[0], b[0]), _join_pmax(a[1], b[1]))

    def copy(self, v):
        return (dict(v[0]), dict(v[1]))

    def canon(self, v) -> tuple:
        return (tuple(sorted(v[0].items())), tuple(sorted(v[1].items())))

    def write(self, cur, rid: int, args: list):
        if len(args) != 1:
            raise ValueError("PNCOUNT write takes: amount (+n or -n)")
        raw = args[0]
        pol = 0
        if raw[:1] == b"-":
            pol, raw = 1, raw[1:]
        elif raw[:1] == b"+":
            raw = raw[1:]
        amount = self._u64(raw)
        cur = cur if cur is not None else ({}, {})
        own = (cur[pol].get(rid, 0) + amount) & U64_MAX
        return ({rid: own}, {}) if pol == 0 else ({}, {rid: own})

    def render(self, resp, v) -> None:
        raw = (sum(v[0].values()) - sum(v[1].values())) & U64_MAX
        resp.i64(raw - (1 << 64) if raw >= (1 << 63) else raw)

    def gen(self, rng):
        g = InnerGCOUNT()
        return (g.gen(rng), g.gen(rng))


# the registered value lattices, by type name. MAP itself is NOT
# registered: the wire unit would nest without bound and the digest
# leaves would lose their (key, field) shape — composition is one
# level deep by design (UJSON already covers arbitrary nesting).
REGISTRY: dict[str, InnerLattice] = {
    inner.name: inner
    for inner in (InnerTREG(), InnerTLOG(), InnerGCOUNT(), InnerPNCOUNT())
}


# ---- composite wire keys ---------------------------------------------------


def pack_field(key: bytes, field: bytes) -> bytes:
    """One opaque wire key for a (key, field) pair: varint key length,
    key, field. Every existing batch mechanism (journal, retransmit
    window, digest tree, range pulls) then operates per FIELD."""
    n = len(key)
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            break
    return bytes(out) + key + field


def unpack_field(packed: bytes) -> tuple[bytes, bytes]:
    """Inverse of pack_field; raises ValueError on a malformed key."""
    shift = n = pos = 0
    while True:
        if pos >= len(packed) or shift > 63:
            raise ValueError("malformed composite key")
        c = packed[pos]
        pos += 1
        n |= (c & 0x7F) << shift
        if not (c & 0x80):
            break
        shift += 7
    if pos + n > len(packed):
        raise ValueError("malformed composite key")
    return packed[pos : pos + n], packed[pos + n :]


# ---- the MAP field lattice -------------------------------------------------


class Field:
    """One field's product-lattice state: inner type tag, per-replica
    edit counters, removal tombstone, inner value. The WIRE unit for a
    field delta is the plain tuple ``(itype, ver, tomb, val)`` —
    :meth:`unit` exports one, :func:`join_units` is the codec-facing
    join over them."""

    __slots__ = ("itype", "ver", "tomb", "val")

    def __init__(self, itype: str, ver=None, tomb=None, val=None):
        inner = REGISTRY[itype]
        self.itype = itype
        self.ver: dict[int, int] = _norm(ver or {})
        self.tomb: dict[int, int] = _norm(tomb or {})
        self.val = val if val is not None else inner.bottom()

    def live(self) -> bool:
        return any(n > self.tomb.get(rid, 0) for rid, n in self.ver.items())

    def unit(self) -> tuple:
        """Export the wire unit (a fresh copy: the caller aliases it
        into journal/broadcast sinks)."""
        inner = REGISTRY[self.itype]
        return (self.itype, dict(self.ver), dict(self.tomb),
                inner.copy(self.val))

    def canon(self) -> tuple:
        return (
            self.itype,
            tuple(sorted(self.ver.items())),
            tuple(sorted(self.tomb.items())),
            REGISTRY[self.itype].canon(self.val),
        )

    def converge_unit(self, unit: tuple) -> None:
        """Join one wire unit in (type dominance, then product join)."""
        itype, ver, tomb, val = unit
        if itype not in REGISTRY:
            raise ValueError(f"unregistered MAP value type: {itype}")
        if itype != self.itype:
            # deterministic type-rank dominance: greater name wins
            # wholesale; the loser's state is discarded identically on
            # every replica, so the composite stays a lattice
            if itype < self.itype:
                return
            inner = REGISTRY[itype]
            self.itype = itype
            self.ver = _norm(ver)
            self.tomb = _norm(tomb)
            self.val = inner.copy(val)
            return
        self.ver = _join_pmax(self.ver, ver)
        self.tomb = _join_pmax(self.tomb, tomb)
        self.val = REGISTRY[itype].join(self.val, val)


def join_units(a: tuple, b: tuple) -> tuple:
    """Join two wire units (the law harness's MAP-field join)."""
    f = Field(a[0], a[1], a[2], REGISTRY[a[0]].copy(a[3]))
    f.converge_unit(b)
    return f.unit()


class MapCRDT:
    """A whole map replica: field name -> Field. The law harness joins
    these (converge) and compares canonical forms; the serving repo
    (models/repo_map.py) keys them per map key."""

    __slots__ = ("fields",)

    def __init__(self):
        self.fields: dict[bytes, Field] = {}

    def set_field(self, field: bytes, rid: int, itype: str, args: list):
        """Local SET: parse the inner write, bump the editor's per-field
        counter, join the content in. Returns the decomposed wire unit
        to ship (ValueError propagates for malformed writes)."""
        f = self.fields.get(field)
        # a type-changing SET starts a fresh dominance contest: the
        # unit carries only this write's evidence
        cur_val = f.val if (f is not None and f.itype == itype) else None
        inner = REGISTRY[itype]  # KeyError = unregistered type
        delta_val = inner.write(cur_val, rid, args)
        if f is None:
            f = Field(itype)
            self.fields[field] = f
        seq = f.ver.get(rid, 0) + 1 if f.itype == itype else 1
        unit = (itype, {rid: seq}, {}, delta_val)
        f.converge_unit(unit)
        return unit

    def del_field(self, field: bytes, rid: int):
        """Local DEL: tombstone every edit this replica has OBSERVED
        (observed-remove: a concurrent unseen edit survives). Returns
        the tombstone-only wire unit to ship, or None if the field is
        unknown/dead (nothing to remove)."""
        f = self.fields.get(field)
        if f is None or not f.live():
            return None
        f.tomb = _join_pmax(f.tomb, f.ver)
        return (f.itype, {}, dict(f.tomb), REGISTRY[f.itype].bottom())

    def get_field(self, field: bytes, itype: str):
        """The live inner value of a field, or None (dead, missing, or
        held by a different dominating type)."""
        f = self.fields.get(field)
        if f is None or f.itype != itype or not f.live():
            return None
        return f.val

    def live_fields(self, itype: str) -> list[bytes]:
        return sorted(
            name
            for name, f in self.fields.items()
            if f.itype == itype and f.live()
        )

    def converge(self, other: "MapCRDT") -> None:
        for name, f in other.fields.items():
            self.converge_field(name, f.unit())

    def converge_field(self, field: bytes, unit: tuple) -> None:
        f = self.fields.get(field)
        if f is None:
            self.fields[field] = Field(
                unit[0], unit[1], unit[2], REGISTRY[unit[0]].copy(unit[3])
            )
        else:
            f.converge_unit(unit)

    def canon(self) -> tuple:
        return tuple(
            (name, f.canon()) for name, f in sorted(self.fields.items())
        )
