"""CRDT lattice kernels.

Each data type is a join-semilattice expressed as pure, jit/vmap-able
functions over struct-of-arrays state (the TPU-native re-design of the
pony-crdt library the reference depends on; semantics pinned by
/root/reference/docs/_docs/types/*.md "Detailed Semantics").

Device kernels:  gcount, pncount, treg, tlog  (dense/padded tensor layouts)
Host lattices:   hostref (pure-Python reference used for differential tests,
                 the SYSTEM log, and the CPU baseline), ujson_host, p2set
"""

from . import gcount, pncount, treg, tlog, hostref, ujson_host, p2set  # noqa: F401
from .interner import Interner  # noqa: F401
