"""TENSOR host lattice: fixed-shape f32 vectors with per-coordinate joins.

The sixth data type (ROADMAP item 3) and the first whose VALUES are
tensors: each key holds a fixed-dimension float32 vector, and the join
is per-coordinate — the workload of "CRDTs for Neural Network Model
Merging" (arXiv:2605.19373) and "Cache Merging as a Convergent
Replicated State for Multi-Agent Latent Reasoning" (arXiv:2607.01308),
where replicated embedding/feature rows converge coordinatewise.

This module is jax-free on purpose: it is the wire-value object the
cluster codec ships (the UJSON precedent — ops/ujson_host.py), the
serving host truth behind models/tensor_table.py, and the lattice the
generated law tests (tests/test_lattice_laws.py) exercise. The batched
device mirror lives in ops/tensor.py.

Three merge modes, all total orders per cell, so every join is a
lattice join by construction:

* ``MAX``  — element-wise maximum. Coordinates are ordered by
  ``okey`` (the order-preserving u32 transform of the f32 bit pattern),
  which totalises IEEE order: ``-0.0 < +0.0`` and the canonical quiet
  NaN sits ABOVE ``+inf`` as the per-coordinate lattice top. Every
  ingest path canonicalises NaN payloads to one bit pattern
  (``0x7FC00000``) so converged replicas are byte-identical.
* ``LWW``  — per-coordinate last-writer-wins: cell B beats cell A iff
  ``(ts_B, rid_B, okey(val_B)) > (ts_A, rid_A, okey(val_A))``. The
  replica-id tiebreak makes equal-timestamp writes from different
  replicas deterministic; the final value-bits tiebreak keeps the order
  total even for adversarial inputs that reuse a (ts, rid) pair.
* ``AVG``  — timestamp-weighted average (arXiv:2605.19373): state is a
  per-replica contribution map ``rid -> (ts, vector)`` joined per rid
  by ``(ts, okey-tuple)`` — a product of total orders — and the READ
  derives ``sum(ts_i * v_i) / sum(ts_i)`` over the converged
  contributions in sorted-rid f64 order, so every converged replica
  renders the same f32 bytes.

Values with different ``(mode, dim)`` stamps are joined by dominance:
the greater ``(mode, dim)`` pair wins wholesale (a lexicographic sum of
lattices over totally-ordered classes — still a lattice). The RESP
boundary REJECTS mode/dim mismatches before they reach the lattice
(models/repo_tensor.py); the dominance rule exists so a malformed or
rolled-upgrade peer can never wedge convergence.

Wire shape (cluster/codec.py delta/TENSOR): every field ships every
time — ``(mode, dim, val, ts, rid, contribs)`` with empty byte strings
for the planes a mode does not use — so the codec's encode/decode
bodies stay branch-free (pass 7's symmetry extractor requires
branch-free units).
"""

from __future__ import annotations

import numpy as np

from ..utils.wire import WireError

MODE_NONE = 0  # unset bottom
MODE_MAX = 1
MODE_LWW = 2
MODE_AVG = 3

MODE_NAMES = {MODE_MAX: b"MAX", MODE_LWW: b"LWW", MODE_AVG: b"AVG"}
MODES_BY_NAME = {v: k for k, v in MODE_NAMES.items()}

_U32 = np.uint32
_EXP_MASK = _U32(0x7F800000)
_MANT_MASK = _U32(0x007FFFFF)
CANON_NAN_BITS = 0x7FC00000  # the one quiet-NaN pattern the lattice keeps

# per-coordinate identity: okey == 0 (below every canonical float)
BOTTOM_BITS = 0xFFFFFFFF


def okey_u32(u: np.ndarray) -> np.ndarray:
    """Order-preserving u32 transform of f32 bit patterns: unsigned
    compares on the result match IEEE order, totalised (-0 < +0, the
    canonical NaN above +inf). Mirrors ops/tensor.py's device _okey."""
    u = np.asarray(u, _U32)
    return np.where(u >> _U32(31), ~u, u | _U32(0x80000000)).astype(_U32)


def canon_f32(raw: bytes) -> bytes:
    """Canonicalise a packed little-endian f32 vector: every NaN payload
    collapses to CANON_NAN_BITS so joins and digests are byte-stable."""
    u = np.frombuffer(raw, "<u4").copy()
    nan = ((u & _EXP_MASK) == _EXP_MASK) & ((u & _MANT_MASK) != 0)
    if nan.any():
        u[nan] = _U32(CANON_NAN_BITS)
    return u.tobytes()


def unpack_f32(raw: bytes) -> list[float]:
    return np.frombuffer(raw, "<f4").astype(float).tolist()


def pack_f32(values) -> bytes:
    return canon_f32(np.asarray(list(values), "<f4").tobytes())


def _okey_tuple(raw: bytes) -> tuple:
    return tuple(okey_u32(np.frombuffer(raw, "<u4")).tolist())


class Tensor:
    """One key's joinable tensor state (and, delta-state style, every
    delta is itself a Tensor)."""

    __slots__ = ("mode", "dim", "val", "ts", "rid", "contribs")

    def __init__(self):
        self.mode = MODE_NONE
        self.dim = 0
        self.val = b""  # (dim,) packed <f4, canonical (MAX/LWW)
        self.ts = b""  # (dim,) packed <u8 (LWW)
        self.rid = b""  # (dim,) packed <u4 (LWW)
        self.contribs: dict[int, tuple[int, bytes]] = {}  # AVG: rid->(ts, vec)

    # ---- constructors ------------------------------------------------------

    @classmethod
    def max_value(cls, raw: bytes) -> "Tensor":
        t = cls()
        t.mode, t.dim, t.val = MODE_MAX, _vec_dim(raw), canon_f32(raw)
        return t

    @classmethod
    def lww(cls, raw: bytes, ts: int, rid: int) -> "Tensor":
        """A whole-vector write: every coordinate stamped (ts, rid)."""
        t = cls()
        t.mode, t.dim, t.val = MODE_LWW, _vec_dim(raw), canon_f32(raw)
        t.ts = np.full(t.dim, ts, "<u8").tobytes()
        t.rid = np.full(t.dim, rid, "<u4").tobytes()
        return t

    @classmethod
    def avg(cls, rid: int, ts: int, raw: bytes) -> "Tensor":
        t = cls()
        t.mode, t.dim = MODE_AVG, _vec_dim(raw)
        t.contribs = {int(rid): (int(ts), canon_f32(raw))}
        return t

    # ---- the lattice join --------------------------------------------------

    def _rank(self) -> tuple[int, int]:
        return (self.mode, self.dim)

    def _copy_from(self, other: "Tensor") -> None:
        self.mode, self.dim = other.mode, other.dim
        self.val, self.ts, self.rid = other.val, other.ts, other.rid
        self.contribs = dict(other.contribs)  # values are immutable tuples

    def converge(self, other: "Tensor") -> bool:
        if other.mode == MODE_NONE or other._rank() < self._rank():
            return False
        if self.mode == MODE_NONE or other._rank() > self._rank():
            self._copy_from(other)
            return True
        if self.mode == MODE_MAX:
            return self._join_max(other)
        if self.mode == MODE_LWW:
            return self._join_lww(other)
        return self._join_avg(other)

    def _join_max(self, other: "Tensor") -> bool:
        a = np.frombuffer(self.val, "<u4")
        b = np.frombuffer(other.val, "<u4")
        take = okey_u32(b) > okey_u32(a)
        if not take.any():
            return False
        self.val = np.where(take, b, a).astype(_U32).tobytes()
        return True

    def _join_lww(self, other: "Tensor") -> bool:
        a_ts = np.frombuffer(self.ts, "<u8")
        b_ts = np.frombuffer(other.ts, "<u8")
        a_rid = np.frombuffer(self.rid, "<u4")
        b_rid = np.frombuffer(other.rid, "<u4")
        a_k = okey_u32(np.frombuffer(self.val, "<u4"))
        b_k = okey_u32(np.frombuffer(other.val, "<u4"))
        ts_eq = a_ts == b_ts
        rid_eq = a_rid == b_rid
        take = (b_ts > a_ts) | (
            ts_eq & ((b_rid > a_rid) | (rid_eq & (b_k > a_k)))
        )
        if not take.any():
            return False
        a_v = np.frombuffer(self.val, "<u4")
        b_v = np.frombuffer(other.val, "<u4")
        self.val = np.where(take, b_v, a_v).astype(_U32).tobytes()
        self.ts = np.where(take, b_ts, a_ts).astype("<u8").tobytes()
        self.rid = np.where(take, b_rid, a_rid).astype(_U32).tobytes()
        return True

    def _join_avg(self, other: "Tensor") -> bool:
        changed = False
        for rid, (ts, vec) in other.contribs.items():
            cur = self.contribs.get(rid)
            if cur is None or (ts, _okey_tuple(vec)) > (
                cur[0], _okey_tuple(cur[1])
            ):
                self.contribs[rid] = (ts, vec)
                changed = True
        return changed

    # ---- reads -------------------------------------------------------------

    def read(self) -> tuple[bytes, int] | None:
        """(rendered vector bytes, newest timestamp), or None when unset.
        Deterministic on every converged replica: AVG sums in f64 over
        sorted rids, MAX reports ts 0 (it carries no clock)."""
        if self.mode == MODE_NONE:
            return None
        if self.mode == MODE_MAX:
            return self.val, 0
        if self.mode == MODE_LWW:
            ts = np.frombuffer(self.ts, "<u8")
            return self.val, int(ts.max()) if ts.size else 0
        acc = np.zeros(self.dim, np.float64)
        wtot = 0.0
        ts_max = 0
        # NaN/inf coordinates propagate through the mean by IEEE rules —
        # deterministic on every replica (sorted-rid f64 accumulation),
        # so the arithmetic warnings are expected, not errors
        with np.errstate(invalid="ignore", over="ignore"):
            for rid in sorted(self.contribs):
                ts, vec = self.contribs[rid]
                w = float(ts)
                acc += w * np.frombuffer(vec, "<f4").astype(np.float64)
                wtot += w
                ts_max = max(ts_max, ts)
            if wtot == 0.0:
                # all-zero weights: fall back to the unweighted mean —
                # from a FRESH accumulator (the weighted pass leaves
                # 0*inf = NaN contamination behind)
                acc = np.zeros(self.dim, np.float64)
                for rid in sorted(self.contribs):
                    acc += np.frombuffer(
                        self.contribs[rid][1], "<f4"
                    ).astype(np.float64)
                wtot = float(len(self.contribs))
            out = (acc / wtot).astype("<f4").tobytes()
        return canon_f32(out), ts_max

    def canon(self) -> tuple:
        """Canonical comparable/digestable form (representation-normal)."""
        return (
            self.mode,
            self.dim,
            self.val,
            self.ts,
            self.rid,
            tuple(sorted(self.contribs.items())),
        )

    # ---- wire validation (cluster/codec.py delta/TENSOR) -------------------

    @classmethod
    def from_wire(
        cls, mode: int, dim: int, val: bytes, ts: bytes, rid: bytes, contribs
    ) -> "Tensor":
        """Rebuild + validate a decoded delta: plane lengths must match
        the mode's shape exactly (a mismatch is wire corruption, not a
        lattice state)."""
        t = cls()
        if mode == MODE_NONE and dim == 0 and not (val or ts or rid or contribs):
            return t
        if mode not in MODE_NAMES or dim < 1:
            raise WireError(f"bad tensor header: mode={mode} dim={dim}")
        want_val = 4 * dim
        if mode == MODE_MAX:
            if len(val) != want_val or ts or rid or contribs:
                raise WireError("MAX tensor plane shape mismatch")
        elif mode == MODE_LWW:
            if len(val) != want_val or len(ts) != 8 * dim or len(rid) != 4 * dim:
                raise WireError("LWW tensor plane shape mismatch")
            if contribs:
                raise WireError("LWW tensor carries contributions")
        else:
            if val or ts or rid or not contribs:
                raise WireError("AVG tensor plane shape mismatch")
            for rid_k, (cts, vec) in contribs.items():
                if rid_k < 0 or len(vec) != want_val:
                    raise WireError("AVG tensor contribution shape mismatch")
                # varints admit ~2^77; the lattice is u64-stamped (the
                # SET path's parse_u64 bound) — an oversized ts would
                # otherwise be accepted, journaled, and re-broadcast,
                # then crash every drain that touches the u64 planes
                if cts > 0xFFFFFFFFFFFFFFFF:
                    raise WireError("AVG tensor contribution ts exceeds u64")
        t.mode, t.dim = mode, dim
        t.val, t.ts, t.rid = canon_f32(val), ts, rid
        t.contribs = {
            int(r): (int(cts), canon_f32(vec))
            for r, (cts, vec) in contribs.items()
        }
        return t

    def __eq__(self, other) -> bool:
        return isinstance(other, Tensor) and self.canon() == other.canon()

    def __hash__(self):
        return hash(self.canon())

    def __repr__(self) -> str:
        return f"Tensor{self.canon()!r}"


def _vec_dim(raw: bytes) -> int:
    if not raw or len(raw) % 4:
        raise ValueError(f"tensor payload must be k*4 bytes, got {len(raw)}")
    return len(raw) // 4
