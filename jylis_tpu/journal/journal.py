"""Append-only framed delta journal: crash durability between snapshots.

Snapshots (persist.py) are periodic full-state dumps, so a whole-node
crash loses every delta accepted since the last one unless a peer holds
it. Delta-state CRDTs make the fix unusually clean (Almeida et al.,
arXiv:1410.2803): a journal of flushed delta BATCHES needs no ordering,
no dedup, and no replay-log semantics — recovery is literally converge,
the same lattice join the cluster codec already exercises. The journal
is the snapshot format's streaming sibling: the same MAGIC-then-
delta-signature header, the same framed ``MsgPushDeltas`` bodies in the
exact cluster wire-delta encoding — guarded by the same schema
signature, so a build whose delta encodings changed refuses the file
instead of corrupting.

File format::

    MAGIC (8 bytes)  codec.delta_signature() (32 bytes)
    frame( crc32(payload):u32be + payload )*    # framing.py frames

where each payload is one ``codec.encode(MsgPushDeltas(name, batch))``.
The one divergence from the snapshot body is the 4-byte CRC inside each
frame: a snapshot is written whole-then-renamed (torn writes impossible,
any decode failure IS corruption), while a journal lives mid-write by
design — the CRC is what separates a mid-file bit flip (refused, file
moved aside) from a torn trailing frame (truncation: appends are
sequential, so a crash mid-append leaves a byte PREFIX of a valid frame
and nothing after it — the tail is cut back to the last complete frame
and recovery proceeds).

Threading: ``append`` only enqueues; a dedicated writer thread does the
encode + write + fsync. The flush paths run on the serving event loop,
and a large TLOG/UJSON batch's wire encode costs tens of milliseconds —
paying that (plus fsync latency) inline would tax every client the loop
is serving (measured: the inline version cost ~20% of `concurrent`
bench throughput; threaded it is ~2%). The writer preserves append
order, ``flush()``/``close()`` drain the queue, and rotation drains
before touching files. The durability point is therefore "flushed, then
journaled within the writer's (millisecond) lag": a SIGKILL loses at
most the still-queued tail — every batch the writer has written is
recoverable under any fsync policy, because each write pushes through
Python's userspace buffer to the OS.

Compaction: the journal grows until ``max_bytes``, then asks for
rotation (``rotate_notify``): the owner cuts a fresh snapshot through
the existing ``persist.write_snapshot`` path AFTER ``rotate_begin()``
renamed the active segment aside — every delta flushed after the cut
lands in the fresh segment and the snapshot covers everything before
it, so snapshot + live segment is complete by construction (overlap is
a lattice no-op). ``rotate_commit()`` retires the old segment only once
the snapshot is durably on disk; a crash anywhere in between leaves the
``.retiring`` segment for boot recovery to replay.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from collections import deque

from .. import faults
from ..cluster import codec
from ..cluster.framing import FrameReader, FramingError, frame
from ..cluster.msg import MsgPushDeltas
from ..utils import metrics

MAGIC = b"JYLJRNL1"
_SIG_LEN = 32
HEADER_LEN = len(MAGIC) + _SIG_LEN
_CRC_LEN = 4

FSYNC_ALWAYS = "always"
FSYNC_INTERVAL = "interval"
FSYNC_OFF = "off"


class JournalError(Exception):
    """Unreadable / corrupt / schema-incompatible journal segment. The
    caller decides whether that is fatal; ``recover`` moves the segment
    aside as ``.unreadable`` (like main.py does for snapshots) and
    boots on."""


# the cluster's held-delta filter and the journal ask the same question
# ("does this batch carry joinable content?") — one shared predicate,
# owned by the codec beside the per-type delta shapes it peeks into
worth_journaling = codec.batch_has_content


class Journal:
    """The append side. One condition variable guards the queue AND the
    file state; the writer thread is the only encoder/writer, so frames
    land in append order without any further coordination."""

    def __init__(
        self,
        path: str,
        fsync: str = FSYNC_INTERVAL,
        fsync_interval: float = 0.2,
        max_bytes: int = 64 << 20,
        clock=time.monotonic,
        registry=None,
    ):
        if fsync not in (FSYNC_ALWAYS, FSYNC_INTERVAL, FSYNC_OFF):
            raise ValueError(f"unknown fsync policy: {fsync}")
        # the owning Database's MetricsRegistry (main.py passes it);
        # registry-less direct drives record into the process DEFAULT —
        # counters, the append/fsync latency histograms, and the trace
        # ring all ride this one handle
        self._reg = registry if registry is not None else metrics.DEFAULT
        self._h_append = self._reg.hist("journal.append")
        self._h_fsync = self._reg.hist("journal.fsync")
        self._path = path
        self._fsync = fsync
        self._fsync_interval = fsync_interval
        self._max_bytes = max_bytes
        self._clock = clock
        self._cv = threading.Condition()
        self._q: deque = deque()
        self._busy = False  # writer mid-encode/mid-write
        self._paused = False  # rotation owns the file; writer sleeps
        self._stop = False
        self._worker: threading.Thread | None = None
        self._f = None
        self._size = 0
        self._last_sync = None
        self._dirty = False  # bytes written since the last fsync
        self._rotation_asked = False
        self.last_error: Exception | None = None  # writer-side encode bug
        # the owner points this at a loop-threadsafe wakeup for the
        # compaction loop; called at most once per threshold crossing
        self.rotate_notify = None

    @property
    def path(self) -> str:
        return self._path

    def retiring_path(self) -> str:
        return self._path + ".retiring"

    # ---- lifecycle ---------------------------------------------------------

    def open(self) -> None:
        """Open (or create) the active segment and start the writer.
        Call AFTER ``recover``: recovery is what validates the header and
        truncates any torn tail; this method trusts an existing
        well-sized file."""
        with self._cv:
            if (
                os.path.exists(self._path)
                and os.path.getsize(self._path) >= HEADER_LEN
            ):
                # boot: no writer thread, no serving loop — jlint: lockio-ok
                self._f = open(self._path, "ab")
                self._size = os.path.getsize(self._path)
            else:
                # jlint: lockio-ok — boot: no writer thread, no serving
                # loop; nothing else can contend for _cv yet
                self._open_fresh_locked()
            self._stop = False
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._run, name="jylis-journal", daemon=True
                )
                self._worker.start()

    def _open_fresh_file(self):
        """Open a fresh segment and write its header; touches NO shared
        state, so both the boot path (under ``_cv``) and rotation (under
        the ``_paused`` hand-off, outside the lock) share it — the one
        place the header bytes are spelled. Returns ``(file, synced_at)``
        where ``synced_at`` is the fsync clock stamp or None."""
        f = open(self._path, "wb")
        try:
            f.write(MAGIC + codec.delta_signature())
            f.flush()
            synced_at = None
            if self._fsync != FSYNC_OFF:
                os.fsync(f.fileno())
                synced_at = self._clock()
        except OSError:
            # a failed header write (ENOSPC) must not leak the fd: the
            # rotation retry path re-opens per attempt, and leaking one
            # per retry would turn a full disk into EMFILE
            f.close()
            raise
        return f, synced_at

    def _open_fresh_locked(self) -> None:
        # boot path: the caller (open) holds _cv and the writer thread
        # does not exist yet, so these stores are serialised. jlint:
        # shared-ok (caller holds _cv)
        self._f, synced_at = self._open_fresh_file()
        if synced_at is not None:
            self._last_sync = synced_at  # jlint: shared-ok (under _cv)
        self._size = HEADER_LEN  # jlint: shared-ok (under _cv)
        self._dirty = False  # jlint: shared-ok (under _cv)
        self._rotation_asked = False  # jlint: shared-ok (under _cv)

    def close(self) -> None:
        """Drain the queue, stop the writer, fsync, close."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        worker = self._worker
        if worker is not None and worker is not threading.current_thread():
            worker.join()
        with self._cv:
            if self._f is None:
                return
            self._f.flush()
            if self._fsync != FSYNC_OFF:
                # terminal: the writer is already joined and appends are
                # rejected, nothing contends for _cv — jlint: lockio-ok
                os.fsync(self._f.fileno())
            self._f.close()
            self._f = None

    def flush(self) -> None:
        """Block until every enqueued batch is on disk (tests, quiesce)."""
        with self._cv:
            self._drain_locked()

    def size(self) -> int:
        with self._cv:
            return self._size

    def needs_rotation(self) -> bool:
        """True when the active segment is at/over the compaction
        threshold — checked by the compaction loop right after it
        installs rotate_notify, so a segment already oversized at boot
        (a crash beat the previous compaction) still rotates."""
        with self._cv:
            return self._size >= self._max_bytes

    # ---- append ------------------------------------------------------------

    def append(self, name: str, batch) -> None:
        """Enqueue one flushed delta batch for the writer thread. The
        caller's ``batch`` is exported, immutable flush output — safe to
        encode later without copying."""
        if not worth_journaling(name, batch):
            return
        with self._cv:
            if self._stop:
                return  # closing: a late flush raced clean shutdown
            self._q.append((name, batch))
            self._cv.notify_all()

    def _drain_locked(self) -> None:
        # _paused too: "drained" must mean on THIS segment's disk, and —
        # since rotate_begin drains first — it is also what serialises
        # two rotations against each other (shutdown's final rotation
        # can overlap the compaction loop's in-flight one: cancelling
        # the loop task cannot stop its to_thread worker)
        while self._q or self._busy or self._paused:
            self._cv.wait()

    # ---- the writer thread -------------------------------------------------

    def _run(self) -> None:
        # While _busy is set, the writer OWNS self._f and the fsync
        # bookkeeping (_last_sync/_dirty): rotation and close wait the
        # flag out before touching the file, so all disk I/O below runs
        # OUTSIDE the condition variable — append() on the serving loop
        # only ever contends for the brief state mutations.
        while True:
            item = None
            idle_sync = False
            with self._cv:
                while self._paused or (not self._q and not self._stop):
                    if self._paused:
                        # rotation owns the file: sleep until it installs
                        # the fresh segment (appends keep enqueueing)
                        self._cv.wait()
                        continue
                    # under the interval policy an unsynced tail must
                    # NOT wait for the next append (the CLI promises a
                    # bounded power-loss window): when idle with dirty
                    # bytes, sleep only until the interval is due and
                    # fsync then
                    wait_s = None
                    if (
                        self._fsync == FSYNC_INTERVAL
                        and self._dirty
                        and self._f is not None
                    ):
                        due = (self._last_sync or 0.0) + self._fsync_interval
                        now = self._clock()
                        if now >= due:
                            idle_sync = True
                            break
                        wait_s = max(due - now, 0.005)
                    self._cv.wait(wait_s)
                if not idle_sync:
                    if not self._q:
                        return  # stopping and drained
                    item = self._q.popleft()
                self._busy = True
                f = self._f
            if idle_sync:
                try:
                    synced = self._sync_file(f)
                    if synced:
                        self._reg.note_journal("fsyncs")
                finally:
                    with self._cv:
                        self._busy = False
                        self._cv.notify_all()
                continue
            name, batch = item
            ask = False
            wrote = 0
            synced = False
            try:
                data = None
                try:
                    payload = codec.encode(MsgPushDeltas(name, tuple(batch)))
                    data = frame(
                        struct.pack(">I", zlib.crc32(payload)) + payload
                    )
                except Exception as e:  # jlint: broad-ok — an encode bug
                    # must not kill the writer thread (a dead writer
                    # silently ends durability); recorded via last_error
                    # and the JOURNAL errors counter
                    self.last_error = e  # jlint: shared-ok (atomic diagnostic ref)
                    self._reg.note_journal("errors")
                    self._reg.trace_event("journal", "error", "encode", repr(e))
                if data is not None and f is None:
                    # no active segment (a failed rotation): the batch
                    # cannot be made durable — count the drop instead of
                    # losing it silently (peers/snapshots still hold it),
                    # and re-ask for rotation: it is what re-opens the
                    # segment, and in size-triggered-only mode
                    # (--snapshot-interval 0) nothing else ever would.
                    # Paced to append cadence, so a dead disk retries
                    # per flush, not in a hot loop.
                    self._reg.note_journal("errors")
                    self._reg.trace_event("journal", "error", "no_segment")
                    with self._cv:
                        if (
                            not self._rotation_asked
                            and self.rotate_notify is not None
                        ):
                            self._rotation_asked = True
                            ask = True
                if data is not None and f is not None:
                    try:
                        # journal.append: error -> the OSError recovery
                        # below (counted, writer survives); corrupt ->
                        # boot replay's CRC refusal; drop -> this batch
                        # silently never reaches disk (peers still hold
                        # it — the drill's local-durability-loss case)
                        data = faults.point("journal.append", data)
                        if data is not None:
                            t0 = time.perf_counter() if self._reg.enabled else 0.0
                            f.write(data)
                            # push past userspace buffering: a SIGKILL
                            # must lose at most the queued tail, never
                            # batches parked in Python's file buffer
                            f.flush()
                            if t0:
                                self._h_append.record(time.perf_counter() - t0)
                            wrote = len(data)
                            # _busy protocol: while set, the writer owns
                            # _f and the fsync bookkeeping — rotation and
                            # close wait the flag out. jlint: shared-ok
                            self._dirty = True
                            if self._fsync == FSYNC_ALWAYS or (
                                self._fsync == FSYNC_INTERVAL
                                and (
                                    self._last_sync is None
                                    or self._clock() - self._last_sync
                                    >= self._fsync_interval
                                )
                            ):
                                synced = self._sync_file(f)
                    except OSError as e:  # full disk etc: keep the writer
                        self.last_error = e  # jlint: shared-ok (atomic diagnostic ref)
                        self._reg.note_journal("errors")
                        self._reg.trace_event("journal", "error", "append", repr(e))
                with self._cv:
                    if wrote:
                        self._size += wrote
                        # latch the rotation request only when someone is
                        # listening: before the compaction loop installs
                        # rotate_notify (or without one at all), latching
                        # would swallow the request for the whole segment
                        # — the loop ALSO checks needs_rotation() when it
                        # installs the hook, covering a journal already
                        # oversized at boot
                        if (
                            self._size >= self._max_bytes
                            and not self._rotation_asked
                            and self.rotate_notify is not None
                        ):
                            self._rotation_asked = True
                            ask = True
                if wrote:
                    self._reg.note_journal("appends")
                    self._reg.note_journal("bytes", wrote)
                if synced:
                    self._reg.note_journal("fsyncs")
                notify = self.rotate_notify
                if ask and notify is not None:
                    notify()
            finally:
                # busy clears only after the metrics/rotation side
                # effects, so flush() returning means they happened too
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def _sync_file(self, f) -> bool:
        """fsync + bookkeeping; writer-thread only (or under drain)."""
        try:
            # journal.fsync: error -> the recovery below (counted, sync
            # skipped, durability window widens); sleep -> a slow disk
            # (writer thread stalls, serving-loop appends keep queueing)
            faults.point("journal.fsync")
            t0 = time.perf_counter() if self._reg.enabled else 0.0
            os.fsync(f.fileno())
            if t0:
                self._h_fsync.record(time.perf_counter() - t0)
        except OSError as e:
            self.last_error = e  # jlint: shared-ok (atomic diagnostic ref)
            self._reg.note_journal("errors")
            self._reg.trace_event("journal", "error", "fsync", repr(e))
            return False
        # writer-owns-file protocol (see _run): only the writer (or a
        # drain-holding caller) reaches here. jlint: shared-ok
        self._last_sync = self._clock()
        self._dirty = False  # jlint: shared-ok (writer owns bookkeeping)
        return True

    # ---- rotation (size-triggered compaction) ------------------------------

    def rotate_begin(self) -> None:
        """Retire the active segment and start a fresh one. The caller
        then cuts a snapshot (persist.write_snapshot) and, on success,
        calls ``rotate_commit``; on failure the retired segment simply
        stays — recovery replays snapshot + retiring + active, and the
        next rotation folds the segments together.

        All disk I/O here runs OUTSIDE the condition variable, under the
        ``_paused`` hand-off: the writer sleeps, ``_f`` is detached, and
        serving-loop ``append()`` calls keep enqueueing at memory speed
        for the whole fsync + fold + rename (jlint JL104 caught the
        previous version holding ``_cv`` across all of it — every
        append, and with it the event loop, stalled behind the disk for
        up to a full 64 MB segment fold)."""
        self._reg.trace_event("journal", "rotate")
        with self._cv:
            self._drain_locked()  # queued batches belong to the OLD cut
            self._paused = True  # writer sleeps; appends only enqueue
            f = self._f
            self._f = None
        fresh = None
        synced_at = None
        try:
            # journal.rotate: error -> the failed-rotation path below
            # (writer resumes with no active segment, re-asks, retries);
            # crash -> dies between drain and rename, leaving .retiring
            # for boot recovery — the exact window the format defends
            faults.point("journal.rotate")
            if f is not None:
                try:
                    f.flush()
                    os.fsync(f.fileno())  # rename only what is durable
                finally:
                    f.close()  # even when the fsync fails: no fd leak
                    # per retry — the segment itself stays on disk for
                    # the next attempt either way
            retiring = self.retiring_path()
            # guard on the active segment existing: a prior failed
            # rotation may have renamed it aside and then died before
            # opening the fresh one — the retry must not wedge on the
            # missing file, just re-open and carry on
            if os.path.exists(self._path):
                if os.path.exists(retiring):
                    # the previous rotation's snapshot never landed: fold
                    # the just-closed segment into the retiring one (both
                    # are valid framed streams with identical headers, so
                    # frames concatenate into a valid stream — join order
                    # is free)
                    with open(self._path, "rb") as src, \
                            open(retiring, "ab") as dst:
                        src.seek(HEADER_LEN)
                        while True:
                            chunk = src.read(1 << 20)
                            if not chunk:
                                break
                            dst.write(chunk)
                        dst.flush()
                        os.fsync(dst.fileno())
                    os.remove(self._path)
                else:
                    os.replace(self._path, retiring)
            fresh, synced_at = self._open_fresh_file()
        except OSError as e:
            # a failed rotation must never leave the writer paused
            # forever: record, resume on whatever file state we reached.
            # ``_f`` may stay None — batches then drain undurable (each
            # counted as a JOURNAL error) until the next successful
            # rotation re-opens the segment; the snapshot loop keeps
            # retrying on its interval
            self.last_error = e  # jlint: shared-ok (atomic diagnostic ref)
            self._reg.note_journal("errors")
            self._reg.trace_event("journal", "error", "rotate", repr(e))
        finally:
            with self._cv:
                self._f = fresh
                if fresh is not None:
                    self._size = HEADER_LEN
                    self._dirty = False
                    if synced_at is not None:
                        self._last_sync = synced_at
                # unlatch even on failure: the writer re-asks on its
                # next undurable drop, which is the retry path that
                # eventually re-opens the segment
                self._rotation_asked = False
                self._paused = False
                self._cv.notify_all()

    def rotate_commit(self) -> None:
        """The snapshot superseding the retired segment is durable:
        delete it. A plain unlink that touches no shared state — taking
        ``_cv`` here would only serialise appends behind the disk."""
        try:
            os.remove(self.retiring_path())
        except FileNotFoundError:
            pass


# ---- replay / recovery ------------------------------------------------------


def read_journal(path: str):
    """Parse one journal segment WITHOUT touching any database: returns
    ``(msgs, good_end, total)`` where ``good_end < total`` means a torn
    trailing frame (bytes past ``good_end`` are a partial frame — crash
    mid-append, not corruption). Raises JournalError on anything else
    unreadable; FileNotFoundError passes through for the caller."""
    with open(path, "rb") as f:
        blob = f.read()
    header = MAGIC + codec.delta_signature()
    if len(blob) < HEADER_LEN:
        # a prefix of a valid header is a file torn during creation —
        # nothing was ever appended; anything else is not a journal
        if blob == header[: len(blob)]:
            return [], 0, len(blob)
        raise JournalError("not a journal file")
    if blob[: len(MAGIC)] != MAGIC:
        raise JournalError("not a journal file")
    accepted = (codec.delta_signature(),) + codec.legacy_delta_signatures()
    if blob[len(MAGIC) : HEADER_LEN] not in accepted:
        # NOT loadable by this build: the caller moves the file aside as
        # .unreadable rather than deleting the only copy. Legacy delta
        # signatures (pre-v7, before delta/TENSOR) ARE loadable: their
        # frames carry only old-type payloads this codec still decodes.
        raise JournalError("journal schema signature mismatch")
    # local-disk read, like snapshots: lift the wire-oriented frame cap
    frames = FrameReader(max_frame=1 << 62)
    frames.append(blob[HEADER_LEN:])
    msgs = []
    try:
        for body in frames:
            if len(body) < _CRC_LEN:
                raise JournalError("corrupt journal: frame shorter than CRC")
            (crc,) = struct.unpack(">I", body[:_CRC_LEN])
            payload = body[_CRC_LEN:]
            if zlib.crc32(payload) != crc:
                raise JournalError("corrupt journal: frame CRC mismatch")
            msg = codec.decode(payload)
            if not isinstance(msg, MsgPushDeltas):
                raise JournalError("unexpected message in journal")
            msgs.append(msg)
    except (codec.CodecError, FramingError) as e:
        # a complete frame that fails to parse can only be corruption:
        # appends are sequential, so torn writes never complete a frame
        raise JournalError(f"corrupt journal: {e}") from None
    return msgs, len(blob) - frames.pending(), len(blob)


def replay_journal(database, path: str, truncate_tail: bool = True) -> int:
    """Converge one journal segment into the database; returns the
    number of batches replayed (0 for a missing file). A torn trailing
    frame is truncation: the file is cut back to its last complete frame
    and everything before it converges. Raises JournalError on any
    OTHER unreadable file — and like snapshot loading, nothing is
    converged unless the readable part fully validates first."""
    try:
        # journal.replay: error -> JournalError -> recover() moves the
        # segment aside (.unreadable) and boots on, healing from peers
        faults.point("journal.replay")
        msgs, good_end, total = read_journal(path)
    except FileNotFoundError:
        return 0
    except OSError as e:
        raise JournalError(f"cannot read journal: {e}") from None
    if truncate_tail and good_end < total:
        os.truncate(path, good_end)
    if truncate_tail and _header_is_legacy(path):
        # a legacy-delta-signature segment is about to be APPENDED to by
        # this build's Journal.open(): re-stamp it in the current schema
        # first, or new-type frames would land in a file whose header
        # promises the old delta encodings (a rolled-back build would
        # then classify the whole segment as corrupt mid-replay instead
        # of refusing it cleanly at the header). Foreign lane segments
        # (truncate_tail=False) belong to live siblings and are never
        # touched.
        _migrate_legacy_segment(path, msgs)
    # fully validated: only now touch the database. load_state (not bare
    # converge) for the same reason snapshots use it: this node's own
    # counter columns are private monotonic state — converging them as
    # foreign would let the next INC vanish under the pending max.
    for msg in msgs:
        database.manager(msg.name).repo.load_state(list(msg.batch))
    if msgs:
        # land replayed state on the device now (persist.py's rationale:
        # a boot-sized host pending buffer taxes every read)
        database.drain_all()
        _db_registry(database).note_journal("replayed_batches", len(msgs))
    return len(msgs)


def _header_is_legacy(path: str) -> bool:
    with open(path, "rb") as f:
        hdr = f.read(HEADER_LEN)
    return (
        len(hdr) == HEADER_LEN
        and hdr[: len(MAGIC)] == MAGIC
        and hdr[len(MAGIC):] != codec.delta_signature()
    )


def _migrate_legacy_segment(path: str, msgs) -> None:
    """Atomically rewrite a validated legacy segment under the CURRENT
    delta signature (same batches, re-encoded — the delta content is
    schema-compatible by the legacy-acceptance contract). Write-then-
    rename like snapshots: a crash leaves either the old valid file or
    the new valid file, never a torn one."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(MAGIC + codec.delta_signature())
        for msg in msgs:
            payload = codec.encode(msg)
            f.write(frame(struct.pack(">I", zlib.crc32(payload)) + payload))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _db_registry(database):
    """The database's MetricsRegistry, or the process DEFAULT for bare
    drivers (the replay helpers take any converge-shaped object)."""
    return metrics.resolve_registry(database)


def segment_name(lane_id: int | None) -> str:
    """The journal segment filename for a lane (None / lane-less nodes
    keep the classic ``journal.jylis``; lane k writes
    ``journal.lane<k>.jylis`` so N lanes append independently)."""
    if lane_id is None:
        return "journal.jylis"
    return f"journal.lane{lane_id}.jylis"


def list_segments(data_dir: str) -> list[str]:
    """Every journal segment path in ``data_dir``, ANY lane naming —
    the classic ``journal.jylis`` plus every ``journal.lane<k>.jylis``
    (``.retiring``/``.unreadable`` variants are handled by recover,
    not listed here). Sorted for deterministic replay order (order is
    a formality: replay is lattice join)."""
    out = []
    for fname in sorted(os.listdir(data_dir)):
        if fname == "journal.jylis" or (
            fname.startswith("journal.lane") and fname.endswith(".jylis")
        ):
            out.append(os.path.join(data_dir, fname))
    return out


def recover_all(database, data_dir: str, own_path: str, log=None) -> int:
    """Boot-path MERGE replay for multi-lane nodes: every lane's
    segment (and its ``.retiring`` sibling) converges into this
    database. Lattice join makes cross-segment overlap harmless, and a
    node rebooted with a DIFFERENT lane count (or ``--lanes 1``) still
    recovers every lane's accepted writes — segments are disjoint by
    acceptance (each lane journals only batches its own serving path
    flushed), and their union is the node's whole journaled state.

    Only the lane's OWN segment (``own_path``) gets the mutating
    recovery (torn-tail truncation, ``.unreadable`` move-aside): a lane
    restarting while its siblings are still serving reads THEIR
    segments mid-append, so a foreign segment's torn tail is the
    owner's live write, not a crash artifact — foreign segments replay
    best-effort with no truncation and no rename, and whatever the
    read missed converges in over the lane bus sync instead."""
    # the own segment recovers unconditionally (its .retiring sibling
    # can exist even when the active file does not — a crash between
    # rotate_begin's rename and the fresh open)
    total = recover(database, own_path, log)
    try:
        segments = list_segments(data_dir)
    except OSError:
        return total
    for path in segments:
        if path == own_path:
            continue
        for p in (path + ".retiring", path):
            try:
                total += replay_journal(database, p, truncate_tail=False)
            except JournalError as e:
                # a foreign lane's problem (or its live mid-write tail):
                # never mutate another lane's file; the owner heals it
                # and the bus sync heals us
                if log is not None:
                    log.warn() and log.w(
                        f"foreign journal segment skipped ({p}): {e}"
                    )
    return total


def recover(database, path: str, log=None) -> int:
    """THE boot-path entry (main.py): replay the retiring segment first
    (present only when a crash interrupted compaction), then the active
    one. An unreadable segment is moved aside as ``.unreadable`` —
    preserving the only copy of whatever it held — and recovery
    continues with the rest; lattice join makes any overlap with the
    snapshot or between segments harmless. Returns batches converged."""
    total = 0
    for p in (path + ".retiring", path):
        try:
            total += replay_journal(database, p)
        except JournalError as e:
            if log is not None:
                log.err() and log.e(f"journal not replayed: {e}")
            _db_registry(database).trace_event(
                "journal", "error", "replay_refused", str(e)
            )
            aside = p + ".unreadable"
            try:
                os.replace(p, aside)
                if log is not None:
                    log.err() and log.e(f"moved aside to {aside}")
            except OSError:
                pass
    return total
