"""Delta write-ahead journal (see journal/journal.py)."""

from .journal import (  # noqa: F401
    FSYNC_ALWAYS,
    FSYNC_INTERVAL,
    FSYNC_OFF,
    HEADER_LEN,
    Journal,
    JournalError,
    MAGIC,
    list_segments,
    recover,
    recover_all,
    segment_name,
    replay_journal,
)
