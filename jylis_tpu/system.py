"""System wrapper: wires the SYSTEM repo into the logger's dual sink.

Reference analog: system.pony:5-41 — every log line is prefixed with this
node's address and appended to the SYSTEM TLog with wall-clock millis, then
trimmed to config.system_log_trim; the same repo serves SYSTEM GETLOG and
rides the anti-entropy path, so `SYSTEM GETLOG` shows the merged recent log
of the whole cluster.
"""

from __future__ import annotations

from .models.repo_system import RepoSYSTEM
from .utils.config import Config


class System:
    def __init__(self, config: Config):
        self.config = config
        self.repo = RepoSYSTEM(config.addr.hash64())
        config.log.set_sys(self.log)

    def log(self, line: str) -> None:
        self.repo.inslog(f"{self.config.addr} {line}")
        self.repo.trimlog(self.config.system_log_trim)
