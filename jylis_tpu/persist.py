"""Snapshot / restore: durability the reference never shipped.

The reference leaves persistence as an explicit TODO
(repo_manager.pony:100,107 "disk persistence?"); its only durability is
replication. This module adds optional snapshots with a CRDT-shaped
design: **a snapshot IS a full-state delta dump** — for every data type,
every key's complete joinable state in the exact per-type wire-delta
format the cluster codec already speaks (cluster/codec.py). Restoring is
just converging the batches back in, so restore composes correctly with
anything that happened meanwhile: load a stale snapshot into a live node
and the lattice join sorts it out — no log replay, no ordering concerns.

File format: magic, the codec DELTA-schema signature (a snapshot whose
per-type delta encodings are incompatible is refused, but transport-only
schema bumps — new message kinds, handshake changes — keep old snapshots
loadable: they contain only delta frames), then one framed MsgPushDeltas
per data type.
"""

from __future__ import annotations

import os

from . import faults
from .cluster import codec
from .cluster.framing import FrameReader, FramingError, frame
from .cluster.msg import MsgPushDeltas

MAGIC = b"JYLSNAP1"

# how many type batches a snapshot of each legacy era actually wrote:
# the v1-v3 full-signature era and the v4-v6 delta-signature era both
# had five data types + SYSTEM; the v7/v8 era added TENSOR. Keyed by
# the header digests in codec.legacy_snapshot_signatures() order
# (v1, v2, v3, v1-v6 delta, v7/v8 delta).
_LEGACY_TYPE_BATCHES = dict(
    zip(codec.legacy_snapshot_signatures(), (6, 6, 6, 6, 7))
)


def save_snapshot(database, path: str) -> None:
    """Atomic (write-then-rename) full-state snapshot of every repo."""
    write_snapshot(
        ((mgr.name, mgr.repo.dump_state()) for mgr in database.managers()),
        path,
    )


def write_snapshot(batches, path: str) -> None:
    """Atomic snapshot from pre-dumped (name, batch) pairs — the online
    snapshot path dumps each type under its own repo lock
    (Database.dump_state_async) and hands the batches here; a crash
    mid-write leaves the previous file intact (write-then-rename)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        f.write(codec.delta_signature())
        for name, batch in batches:
            # snapshot.write (per type frame): error -> OSError out of
            # here, the snapshot loop / shutdown path logs and the
            # journal keeps the deltas; corrupt/drop -> the NEXT boot's
            # load validation refuses the file and moves it aside
            data = faults.point(
                "snapshot.write",
                frame(codec.encode(MsgPushDeltas(name, tuple(batch)))),
            )
            if data is not None:
                f.write(data)
    os.replace(tmp, path)


class SnapshotError(Exception):
    pass


def load_snapshot(database, path: str) -> int:
    """Converge a snapshot file into the database; returns the number of
    type-batches loaded. Raises SnapshotError on ANY unreadable, corrupt,
    incompatible, or incomplete file (the caller decides whether that is
    fatal — nothing is converged unless the whole file validates)."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
        # snapshot.load: error -> "cannot read" below; corrupt -> the
        # validation path refuses (caller moves the file aside, node
        # heals from peers); drop -> treated as unreadable
        blob = faults.point("snapshot.load", blob)
    except OSError as e:
        raise SnapshotError(f"cannot read snapshot: {e}") from None
    if blob is None:
        raise SnapshotError("snapshot dropped by failpoint")
    if blob[: len(MAGIC)] != MAGIC:
        raise SnapshotError("not a snapshot file")
    sig_end = len(MAGIC) + len(codec.delta_signature())
    header = blob[len(MAGIC) : sig_end]
    accepted = (codec.delta_signature(),) + codec.legacy_snapshot_signatures()
    if header not in accepted:
        # NOT recoverable by this build: main.py moves the file aside as
        # .unreadable rather than deleting it
        raise SnapshotError("snapshot schema signature mismatch")
    # snapshots are read whole from local disk: no adversarial peer to
    # bound against, so lift the wire-oriented frame cap
    frames = FrameReader(max_frame=1 << 62)
    frames.append(blob[sig_end:])
    msgs = []
    try:
        for body in frames:
            msg = codec.decode(body)
            if not isinstance(msg, MsgPushDeltas):
                raise SnapshotError("unexpected message in snapshot")
            msgs.append(msg)
    except (codec.CodecError, FramingError) as e:
        raise SnapshotError(f"corrupt snapshot: {e}") from None
    if frames.pending():
        raise SnapshotError("truncated snapshot (partial trailing frame)")
    expected = len(list(database.managers()))
    if header == codec.delta_signature():
        if len(msgs) != expected:
            raise SnapshotError(
                f"snapshot has {len(msgs)} type batches, expected "
                f"{expected} (truncated at a frame boundary?)"
            )
    else:
        # a legacy-era snapshot carries EXACTLY its era's type count
        # (types added since then are simply not in the file) — the
        # exact check keeps frame-boundary truncation detectable for
        # legacy files too. The current count is also accepted: a
        # current-shape file under a legacy header is byte-loadable
        # (the delta encodings it names are a subset), and the legacy
        # round-trip tests exercise exactly that shape.
        era = _LEGACY_TYPE_BATCHES.get(header)
        allowed = {expected} if era is None else {era, expected}
        if len(msgs) not in allowed:
            raise SnapshotError(
                f"legacy snapshot has {len(msgs)} type batches, "
                f"expected one of {sorted(allowed)} (truncated at a "
                "frame boundary?)"
            )
    # fully validated: only now touch the database
    for msg in msgs:
        database.manager(msg.name).repo.load_state(list(msg.batch))
    # restored state lands on the device NOW: converge only buffers, and
    # leaving a whole snapshot in host pending buffers would bypass the
    # drain thresholds and tax every read with the merge path
    database.drain_all()
    return len(msgs)
