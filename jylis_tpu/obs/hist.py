"""Fixed-bucket log2 latency histogram.

64 power-of-two nanosecond buckets: bucket 0 holds exact zeros, bucket
i (1..63) holds durations in [2^(i-1), 2^i) ns, with everything past
~2^62 ns clamped into the last bucket. `record` is one float→int
conversion, one `int.bit_length`, and one list increment — no
allocation, no branching on the data, so the seams stay armed on the
serving hot path permanently (bench.py's `obs_cost_frac` records the
measured cost).

Quantile queries walk the 64 buckets and report the matched bucket's
UPPER bound, so the reported value is within one bucket (a factor of
two) above the true sample — a deliberate over- rather than
under-report for a latency surface (tests/test_obs.py pins the bound
against numpy percentiles on adversarial distributions).

Thread model: `record` fires from the event loop AND from worker
threads (journal writer, threaded drains). The increments are plain
GIL-interleaved operations; a lost update under contention skews a
count by one, which is acceptable for a metrics surface and the price
of keeping the hot path lock-free.
"""

from __future__ import annotations

N_BUCKETS = 64


class Histogram:
    __slots__ = ("buckets", "count", "total", "max")

    def __init__(self):
        self.buckets = [0] * N_BUCKETS
        self.count = 0
        self.total = 0.0  # seconds, for Prometheus summary _sum
        self.max = 0.0  # seconds

    def record(self, seconds: float) -> None:
        ns = int(seconds * 1e9)
        if ns < 0:  # clock hiccup: bucket as zero rather than crash
            ns = 0
        i = ns.bit_length()
        if i > N_BUCKETS - 1:
            i = N_BUCKETS - 1
        self.buckets[i] += 1
        self.count += 1
        self.total += seconds
        if seconds > self.max:
            self.max = seconds

    def percentile(self, q: float) -> float:
        """The q-quantile (0 < q <= 1) in SECONDS: the upper bound of
        the bucket holding the ceil(q * count)-th sample, 0.0 when
        empty."""
        return percentile_of(self.buckets, self.count, q)

    def snapshot(self) -> dict:
        """One consistent-enough view for the reporting surfaces:
        {count, sum_s, max_s, p50_s, p90_s, p99_s}."""
        return {
            "count": self.count,
            "sum_s": self.total,
            "max_s": self.max,
            "p50_s": self.percentile(0.50),
            "p90_s": self.percentile(0.90),
            "p99_s": self.percentile(0.99),
        }

    def mark(self) -> tuple:
        """A cheap point-in-time copy for windowed (delta-since-mark)
        quantiles: (buckets copy, count, total)."""
        return (list(self.buckets), self.count, self.total)

    def snapshot_since(self, marked: tuple) -> dict:
        """snapshot() over only the samples recorded AFTER ``marked``
        (a prior mark() of this histogram). Since-boot buckets are
        monotone, so the bucket-wise difference IS the window's
        histogram. No max_s: the since-boot max can't be windowed."""
        mbuckets, mcount, mtotal = marked
        buckets = [a - b for a, b in zip(self.buckets, mbuckets)]
        count = self.count - mcount
        return {
            "count": count,
            "sum_s": self.total - mtotal,
            "p50_s": percentile_of(buckets, count, 0.50),
            "p90_s": percentile_of(buckets, count, 0.90),
            "p99_s": percentile_of(buckets, count, 0.99),
        }


def percentile_of(buckets: list, count: int, q: float) -> float:
    """The quantile walk over an arbitrary bucket vector (shared by the
    live histogram and windowed bucket differences)."""
    if count <= 0:
        return 0.0
    target = q * count
    cum = 0
    for i, c in enumerate(buckets):
        cum += c
        if cum >= target:
            return 0.0 if i == 0 else float(1 << i) * 1e-9
    return float(1 << (N_BUCKETS - 1)) * 1e-9  # racing counts: clamp


def bucket_upper_seconds(i: int) -> float:
    """Bucket i's inclusive upper bound in seconds — the Prometheus
    ``le`` label for the cumulative `_bucket` exposition (bucket 0 is
    the exact-zero bucket; its bound is 0)."""
    return 0.0 if i == 0 else ((1 << i) - 1) * 1e-9
