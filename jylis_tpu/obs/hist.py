"""Fixed-bucket log2 latency histogram.

64 power-of-two nanosecond buckets: bucket 0 holds exact zeros, bucket
i (1..63) holds durations in [2^(i-1), 2^i) ns, with everything past
~2^62 ns clamped into the last bucket. `record` is one float→int
conversion, one `int.bit_length`, and one list increment — no
allocation, no branching on the data, so the seams stay armed on the
serving hot path permanently (bench.py's `obs_cost_frac` records the
measured cost).

Quantile queries walk the 64 buckets and report the matched bucket's
UPPER bound, so the reported value is within one bucket (a factor of
two) above the true sample — a deliberate over- rather than
under-report for a latency surface (tests/test_obs.py pins the bound
against numpy percentiles on adversarial distributions).

Thread model: `record` fires from the event loop AND from worker
threads (journal writer, threaded drains). The increments are plain
GIL-interleaved operations; a lost update under contention skews a
count by one, which is acceptable for a metrics surface and the price
of keeping the hot path lock-free.
"""

from __future__ import annotations

N_BUCKETS = 64


class Histogram:
    __slots__ = ("buckets", "count", "total", "max")

    def __init__(self):
        self.buckets = [0] * N_BUCKETS
        self.count = 0
        self.total = 0.0  # seconds, for Prometheus summary _sum
        self.max = 0.0  # seconds

    def record(self, seconds: float) -> None:
        ns = int(seconds * 1e9)
        if ns < 0:  # clock hiccup: bucket as zero rather than crash
            ns = 0
        i = ns.bit_length()
        if i > N_BUCKETS - 1:
            i = N_BUCKETS - 1
        self.buckets[i] += 1
        self.count += 1
        self.total += seconds
        if seconds > self.max:
            self.max = seconds

    def percentile(self, q: float) -> float:
        """The q-quantile (0 < q <= 1) in SECONDS: the upper bound of
        the bucket holding the ceil(q * count)-th sample, 0.0 when
        empty."""
        n = self.count
        if n == 0:
            return 0.0
        target = q * n
        cum = 0
        for i, c in enumerate(self.buckets):
            cum += c
            if cum >= target:
                return 0.0 if i == 0 else float(1 << i) * 1e-9
        return float(1 << (N_BUCKETS - 1)) * 1e-9  # racing counts: clamp

    def snapshot(self) -> dict:
        """One consistent-enough view for the reporting surfaces:
        {count, sum_s, max_s, p50_s, p90_s, p99_s}."""
        return {
            "count": self.count,
            "sum_s": self.total,
            "max_s": self.max,
            "p50_s": self.percentile(0.50),
            "p90_s": self.percentile(0.90),
            "p99_s": self.percentile(0.99),
        }
