"""jtrace: sampled end-to-end delta provenance spans.

A span is a tiny append-only byte string carried on SEQUENCED cluster
frames (schema v11's transport-only ``span`` field — delta signatures
untouched). The origin node mints one for 1-in-N sequenced flushes
(``--trace-sample``); every hop the frame crosses appends a stamp
(origin lane, lane bus, external cluster, bridge relay), and the final
receiver appends its apply stamp and folds the whole chain into
convergence-latency histograms — per hop transition, and end-to-end per
(origin region, apply region) pair. The worst chains seen are kept as
exemplars and surfaced via ``SYSTEM TRACE SPANS``; the fold also feeds
the ``converge_slo`` gauge family (fraction of sampled deltas fully
applied within each configured threshold, ``--converge-slo-ms``).

Wire format (LEB128, same primitives as the cluster codec):

    span  = hop*
    hop   = tag:varint len:varint payload[len]
    payload = rid:str region:str ts_ms:varint

``len`` frames each hop so UNKNOWN tags from newer nodes are skipped,
not fatal — the same forward-compatibility discipline the delta codec
uses for unknown type names. Decoding is defensive the way the TENSOR
AVG-ts lesson taught: truncation anywhere raises WireError, ``ts_ms``
is u64-bounded, and the hop count is capped (a span is at most a few
hops; an unbounded one is an attack or a bug, either way droppable).
Spans ride INSIDE the CRC-covered frame body, so a fold failure is
counted as ``malformed`` and never harms the frame's deltas.

Retransmits replay the originally wired bytes (the delta log stores
wired frames), so a retransmitted sample carries its original stamps —
its measured latency honestly includes the loss it survived.
"""

from __future__ import annotations

import threading

from ..utils.wire import Reader, WireError
from .hist import Histogram

# hop tags, in the order a write crosses them
HOP_ORIGIN = 1  # minted where broadcast_deltas sequenced the flush
HOP_BUS = 2  # the lane bus (intra-node fan-out between lanes)
HOP_CLUSTER = 3  # the external WAN cluster leg (lane 0's bridge tee)
HOP_RELAY = 4  # a bridge relayed it onward (origin-preserving)
HOP_APPLY = 5  # the receiving replica applied it (appended at fold)

_HOP_NAMES = {
    HOP_ORIGIN: "origin",
    HOP_BUS: "bus",
    HOP_CLUSTER: "cluster",
    HOP_RELAY: "relay",
    HOP_APPLY: "apply",
}

MAX_HOPS = 32  # a real chain is ≤ ~6; anything longer is garbage
_U64_MAX = (1 << 64) - 1

DEFAULT_SLO_MS = (50, 250, 1000)
WORST_KEEP = 8  # exemplar chains retained for SYSTEM TRACE SPANS


def hop_name(tag: int) -> str:
    return _HOP_NAMES.get(tag, f"hop{tag}")


def _w_varint(acc: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            acc.append(b | 0x80)
        else:
            acc.append(b)
            return


def append_hop(span: bytes, tag: int, rid: str, region: str,
               ts_ms: int) -> bytes:
    """Return ``span`` with one hop stamp appended (pure — the original
    bytes are never mutated; a relayed frame re-encodes its message)."""
    payload = bytearray()
    rb = rid.encode()
    _w_varint(payload, len(rb))
    payload += rb
    gb = region.encode()
    _w_varint(payload, len(gb))
    payload += gb
    _w_varint(payload, max(0, ts_ms) & _U64_MAX)
    acc = bytearray(span)
    _w_varint(acc, tag)
    _w_varint(acc, len(payload))
    acc += payload
    return bytes(acc)


def decode_span(span: bytes) -> list[tuple[int, str, str, int]]:
    """Decode a span to ``[(tag, rid, region, ts_ms), ...]``.

    Unknown hop tags are skipped via their length prefix; truncation,
    u64 overflow, or an absurd hop count raise WireError.
    """
    r = Reader(span)
    hops: list[tuple[int, str, str, int]] = []
    n_seen = 0
    while not r.done():
        tag = r.varint()
        if tag > _U64_MAX:
            raise WireError("span hop tag out of u64 range")
        body = r.bytes_()
        n_seen += 1
        if n_seen > MAX_HOPS:
            raise WireError("span hop count over bound")
        if tag not in _HOP_NAMES:
            continue  # forward compat: a newer node's hop kind
        hr = Reader(body)
        rid = hr.str_()
        region = hr.str_()
        ts = hr.varint()
        if ts > _U64_MAX:
            raise WireError("span hop ts out of u64 range")
        # trailing payload bytes are tolerated (a newer node may extend
        # a KNOWN hop's payload; the length prefix already framed it)
        hops.append((tag, rid, region, ts))
    return hops


def format_chain(hops: list[tuple[int, str, str, int]]) -> str:
    """``origin@rid[r1]+0ms -> relay@rid2[r1]+3ms -> apply@rid3[r2]+9ms``
    — per-hop offsets from the origin stamp (clock-skew caveat applies
    exactly as it does to converge_lag_ms)."""
    if not hops:
        return "(empty span)"
    t0 = hops[0][3]
    parts = []
    for tag, rid, region, ts in hops:
        where = f"{rid}[{region}]" if region else rid
        parts.append(f"{hop_name(tag)}@{where}+{max(0, ts - t0)}ms")
    return " -> ".join(parts)


class SpanStats:
    """Fold arrived spans into per-hop and end-to-end latency
    histograms, SLO counters, and worst-chain exemplars.

    NOT named like registry histograms on purpose: metric names here
    are data-dependent (region pairs, hop transitions), and jlint
    pass 5 rightly refuses dynamic names through hist()/gauge_set().
    This class IS the declared surface — prom.py renders it wholesale.

    Thread-safe under a lock: lanes fold on their own loop threads, and
    SYSTEM TRACE SPANS / the scrape read from another.
    """

    def __init__(self, slo_ms: tuple[int, ...] = DEFAULT_SLO_MS):
        self._lock = threading.Lock()
        self.slo_ms: tuple[int, ...] = tuple(sorted(slo_ms))
        self.sampled = 0  # spans folded (chain decoded fine)
        self.malformed = 0  # spans dropped by the defensive decoder
        self.slo_ok = [0] * len(self.slo_ms)
        # (from_tag, to_tag) -> Histogram of the transition latency
        self.hop_hists: dict[tuple[int, int], Histogram] = {}
        # (origin_region, apply_region) -> Histogram of e2e latency
        self.e2e_hists: dict[tuple[str, str], Histogram] = {}
        # worst end-to-end chains seen: [(e2e_ms, formatted chain)]
        self.worst: list[tuple[int, str]] = []

    def set_slo_ms(self, slo_ms: tuple[int, ...]) -> None:
        with self._lock:
            self.slo_ms = tuple(sorted(slo_ms))
            self.slo_ok = [0] * len(self.slo_ms)

    def ingest(self, span: bytes, rid: str, region: str,
               now_ms: int) -> str | None:
        """Fold one arrived span; ``rid``/``region``/``now_ms`` stamp
        the local apply hop. Returns the formatted chain if it set a
        new worst-e2e record (caller traces it), else None."""
        try:
            hops = decode_span(span)
        except WireError:
            with self._lock:
                self.malformed += 1
            return None
        if not hops or hops[0][0] != HOP_ORIGIN:
            # a chain with no origin stamp can't be timed end to end
            with self._lock:
                self.malformed += 1
            return None
        hops.append((HOP_APPLY, rid, region, now_ms))
        t_origin = hops[0][3]
        e2e_ms = max(0, now_ms - t_origin)
        pair = (hops[0][2], region)
        chain = None
        with self._lock:
            self.sampled += 1
            for i, ms in enumerate(self.slo_ms):
                if e2e_ms <= ms:
                    self.slo_ok[i] += 1
            h = self.e2e_hists.get(pair)
            if h is None:
                h = self.e2e_hists[pair] = Histogram()
            h.record(e2e_ms * 1e-3)
            for (ptag, _, _, pts), (tag, _, _, ts) in zip(hops, hops[1:]):
                key = (ptag, tag)
                th = self.hop_hists.get(key)
                if th is None:
                    th = self.hop_hists[key] = Histogram()
                th.record(max(0, ts - pts) * 1e-3)
            floor = self.worst[-1][0] if len(self.worst) >= WORST_KEEP \
                else -1
            if e2e_ms > floor or len(self.worst) < WORST_KEEP:
                chain = format_chain(hops)
                self.worst.append((e2e_ms, chain))
                self.worst.sort(key=lambda w: -w[0])
                is_record = self.worst[0][1] == chain
                del self.worst[WORST_KEEP:]
                if not is_record:
                    chain = None
        return chain

    def slo_fracs(self) -> list[tuple[int, float, int]]:
        """[(threshold_ms, fraction_ok, ok_count)] over sampled spans."""
        with self._lock:
            n = max(self.sampled, 1)
            return [
                (ms, self.slo_ok[i] / n, self.slo_ok[i])
                for i, ms in enumerate(self.slo_ms)
            ]

    def report_lines(self) -> list[str]:
        """The SYSTEM TRACE SPANS body: counters, per-hop-transition
        and per-region-pair latency lines, SLO fractions, exemplars."""
        with self._lock:
            lines = [
                f"spans sampled {self.sampled} malformed {self.malformed}"
            ]
            for (a, b), h in sorted(self.hop_hists.items()):
                s = h.snapshot()
                lines.append(
                    f"hop {hop_name(a)}->{hop_name(b)} count {s['count']}"
                    f" p50_ms {s['p50_s'] * 1e3:.3f}"
                    f" p99_ms {s['p99_s'] * 1e3:.3f}"
                    f" max_ms {s['max_s'] * 1e3:.3f}"
                )
            for (src, dst), h in sorted(self.e2e_hists.items()):
                s = h.snapshot()
                lines.append(
                    f"e2e {src or '-'}->{dst or '-'} count {s['count']}"
                    f" p50_ms {s['p50_s'] * 1e3:.3f}"
                    f" p99_ms {s['p99_s'] * 1e3:.3f}"
                    f" max_ms {s['max_s'] * 1e3:.3f}"
                )
            n = max(self.sampled, 1)
            for i, ms in enumerate(self.slo_ms):
                lines.append(
                    f"slo {ms}ms frac {self.slo_ok[i] / n:.4f}"
                    f" ok {self.slo_ok[i]}"
                )
            for e2e_ms, chain in self.worst:
                lines.append(f"worst {e2e_ms}ms {chain}")
            return lines
