"""Per-Database metrics registry.

Before this class the drain / journal / serving counters were
process-global module state in utils/metrics.py, with a documented
caveat: multiple Databases in one process (tests, benches, the warmup
throwaway) cross-talked through them. The registry makes the whole
observability surface — counters, histograms, gauges, trace ring — a
per-`Database` instance passed down explicitly: Database creates one,
hands it to its repos (drain timing), the Server (dispatch seams), the
Journal (append/fsync seams), and the Cluster (round-trip + convergence
lag), and RepoSYSTEM reads it for `SYSTEM METRICS` / `LATENCY` /
`TRACE`. utils/metrics.py keeps a process-wide DEFAULT instance so
registry-less direct drives (standalone repos, a bare Journal) still
record somewhere.

``enabled`` is the one global switch the seams check before paying for
`perf_counter` pairs: bench.py flips it off for the `obs_cost_frac`
comparison run, so the recorded overhead covers the FULL cost of
observation (clock reads included), not just the bucket increment.

Histogram and gauge names are pre-registered from obs.SEAMS/GAUGES —
`hist()` raises KeyError on an undeclared name, and jlint pass 5
(JL501/JL502) holds the call-site literals, the declarations, and the
manifest descriptions in lockstep.
"""

from __future__ import annotations

import time
from collections import defaultdict, deque

from . import GAUGES, SEAMS
from .hist import Histogram
from .jtrace import SpanStats
from .trace import TraceRing

JOURNAL_KEYS = ("appends", "bytes", "fsyncs", "replayed_batches", "errors")

# windowed-quantile marks: how many point-in-time seam copies we keep,
# and the minimum spacing between deposits (an opportunistic deposit on
# every scrape/SYSTEM LATENCY call must not grow cost with poll rate)
WINDOW_MARKS = 64
WINDOW_MIN_SPACING_S = 1.0

HEAT_FANOUT = 256  # digest-tree leaf fanout (models/database.py SYNC_FANOUT)


class MetricsRegistry:
    def __init__(self, trace_cap: int = 512):
        self.enabled = True
        # per-type device drain accumulators (batches / keys / seconds)
        self.counters: dict[str, dict[str, float]] = defaultdict(
            lambda: {"batches": 0, "keys": 0, "seconds": 0.0}
        )
        # delta write-ahead journal counters: appends / bytes / fsyncs
        # accrue on the writer thread, replayed_batches on boot
        # recovery, errors on ANY writer-side encode/write/fsync failure
        self.journal_counters: dict[str, int] = dict.fromkeys(JOURNAL_KEYS, 0)
        # True once a journal is attached (Database.set_journal): the
        # JOURNAL section of SYSTEM METRICS then shows explicit zeros
        # from boot instead of appearing at the first nonzero counter
        self.journal_enabled = False
        # serving-path: whole-connection demotions off the native engine
        # + per-command-class admission-control refusals (manager.py)
        self.serving_counters: dict[str, int] = {
            "demotions": 0,
            "busy_refusals": 0,
        }
        self.hists: dict[str, Histogram] = {name: Histogram() for name in SEAMS}
        self.gauges: dict[str, float] = {name: 0.0 for name in GAUGES}
        self.trace = TraceRing(trace_cap)
        # provenance-span folds (obs/jtrace.py): per-hop + per-region-
        # pair convergence histograms, SLO counters, worst exemplars
        self.spans = SpanStats()
        # per-digest-tree-bucket write heat: type -> 256 counters over
        # sha256(key)[0], counted where deltas are emitted (manager.py
        # _emit) — the placement telemetry ROADMAP item 3 needs
        self.write_heat: dict[str, list[int]] = {}
        # windowed quantiles: (monotonic ts, {seam: Histogram.mark()})
        self._window_marks: deque = deque(maxlen=WINDOW_MARKS)

    # ---- counters ----------------------------------------------------------

    def note_drain(self, name: str, n_keys: int, seconds: float) -> None:
        c = self.counters[name]
        c["batches"] += 1
        c["keys"] += n_keys
        c["seconds"] += seconds
        h = self.hists.get("drain." + name)
        if h is not None:
            h.record(seconds)

    def note_journal(self, counter: str, n: int = 1) -> None:
        self.journal_counters[counter] += n

    def note_serving(self, counter: str, n: int = 1) -> None:
        self.serving_counters[counter] += n

    def note_write_heat(self, name: str, bucket: int, n: int = 1) -> None:
        """One emitted delta batch touched ``bucket`` of ``name``'s
        digest tree (0..255). Lazy per-type vectors: a type that never
        writes costs nothing."""
        heat = self.write_heat.get(name)
        if heat is None:
            heat = self.write_heat[name] = [0] * HEAT_FANOUT
        heat[bucket] += n

    # ---- histograms / gauges / trace --------------------------------------

    def hist(self, name: str) -> Histogram:
        return self.hists[name]  # KeyError = undeclared seam, fail loud

    def gauge_set(self, name: str, value: float) -> None:
        if name not in self.gauges:
            raise KeyError(name)  # undeclared gauge, fail loud
        self.gauges[name] = value

    def trace_event(
        self, subsystem: str, event: str, reason: str = "", detail: str = ""
    ) -> None:
        if self.enabled:
            self.trace.push(subsystem, event, reason, detail)

    # ---- reporting ---------------------------------------------------------

    def type_stats(self):
        """(name, drains, keys, device_ms) per drained type — the ONE
        iteration the reporting surfaces share. list() snapshots the key
        set atomically under the GIL: note_drain runs in worker threads
        and may insert a type's key mid-request."""
        for name in sorted(list(self.counters)):
            c = self.counters.get(name)
            if c is not None:
                yield name, int(c["batches"]), int(c["keys"]), c["seconds"] * 1e3

    def seam_stats(self):
        """(name, snapshot) per declared seam, SEAMS order."""
        for name in SEAMS:
            yield name, self.hists[name].snapshot()

    # ---- windowed quantiles ------------------------------------------------

    def window_deposit(self) -> None:
        """Opportunistically deposit a point-in-time mark of every seam
        (called from the reporting surfaces — SYSTEM LATENCY, the
        scrape — never the hot path). Rate-limited so poll frequency
        can't inflate the cost; the ring keeps ~the last minute."""
        now = time.monotonic()
        if self._window_marks and (
            now - self._window_marks[-1][0] < WINDOW_MIN_SPACING_S
        ):
            return
        self._window_marks.append(
            (now, {name: self.hists[name].mark() for name in SEAMS})
        )

    def window_stats(self, seconds: float):
        """(achieved_window_s, [(name, delta_snapshot), ...]) against
        the deposited mark closest to ``seconds`` ago — delta-since-mark
        quantiles, so a regression on a long-running node isn't drowned
        by since-boot history. Returns (0.0, None) when no mark is old
        enough to subtract (callers report 'no window yet')."""
        if not self._window_marks:
            return 0.0, None
        now = time.monotonic()
        best = min(
            self._window_marks,
            key=lambda m: abs((now - m[0]) - seconds),
        )
        achieved = now - best[0]
        if achieved <= 0.0:
            return 0.0, None
        marks = best[1]
        return achieved, [
            (name, self.hists[name].snapshot_since(marks[name]))
            for name in SEAMS
        ]

    def report(self) -> str:
        parts = [
            f"{name}: {drains} drains, {keys} keys, {ms:.1f}ms device"
            for name, drains, keys, ms in self.type_stats()
        ]
        return "; ".join(parts) if parts else "no drains"
