"""Always-on node observability: latency histograms, convergence lag,
and a bounded structured trace ring.

Until this package, every latency number the repo could show was
measured from OUTSIDE by bench.py, and `SYSTEM METRICS` was monotonic
counters only — the node itself could not answer "how long does a drain
take at p99?" or "how stale is the data a peer pushed me?". The
delta-CRDT literature frames exactly those two quantities as THE trade
the model makes (Almeida et al., arXiv:1410.2803: anti-entropy cost vs
staleness; Big(ger) Sets, arXiv:1605.06424: per-replica propagation
backlog), so they must be live on the node, not in offline bench
records. Three pillars:

* **Fixed-bucket log2 latency histograms** (`hist.Histogram`): 64
  power-of-two nanosecond buckets, record = one index computation + one
  list increment, no allocation — cheap enough to stay armed on the
  serving hot path permanently (bench.py records `obs_cost_frac` to
  prove it). Wired into every timed seam the repo already has: native
  burst + Python dispatch (server), per-type device drains
  (utils/metrics.timed_drain), journal append/fsync, and cluster
  heartbeat round-trips.
* **Convergence-lag tracking**: every cluster transport frame carries
  its sender's wall-clock origin (schema v6, cluster/cluster.py);
  receivers record push→apply lag per peer into a `converge_lag_ms`
  gauge (EWMA) plus a node-wide anti-entropy `backlog_ms` gauge — the
  time dimension of the held-delta / deferred-sync counts the CLUSTER
  metrics section already carries.
* **A bounded structured trace ring** (`trace.TraceRing`): fixed-size
  deque of (ts_ms, subsystem, event, reason, detail) tuples fed by the
  same seams the failpoints manifest names, dumped by `SYSTEM TRACE
  [count]` and automatically on unclean shutdown.

Everything surfaces three ways: extended `SYSTEM METRICS` lines, the
`SYSTEM LATENCY` subcommand, and the opt-in `--metrics-port` HTTP
endpoint emitting Prometheus text exposition (`prom.py`).

Naming discipline: every histogram/gauge/trace-event name is a string
literal at its call site, declared and described in
`scripts/jlint/metrics_manifest.json` (jlint pass 5, rules
JL501/JL502), and every histogram/gauge is pre-registered below so a
scrape shows the full surface (with zero counts) from boot.
"""

from __future__ import annotations

# Every latency histogram seam, pre-created in each MetricsRegistry so
# the Prometheus scrape and SYSTEM LATENCY show the complete surface
# from boot (zero counts included). jlint pass 5 cross-checks this
# tuple against the literal names at the call sites.
SEAMS = (
    "drain.TREG",
    "drain.TLOG",
    "drain.GCOUNT",
    "drain.PNCOUNT",
    "drain.TENSOR",
    "drain.MAP",
    "drain.BCOUNT",
    "server.native_burst",
    "server.py_dispatch",
    "journal.append",
    "journal.fsync",
    "cluster.rtt",
    "cluster.converge_lag",
    # the serving-pipeline profiler (server.py): per-stage timers on
    # the RESP path, so ROADMAP item 1's socket-tax attribution is a
    # measured per-stage split instead of one bench-derived ratio.
    # Stage semantics (docs/observability.md): accept = connection
    # setup (one sample per conn), read = one socket read await
    # (includes client idle — meaningful under saturation), parse =
    # one Python-path command parse, classify = admission classify +
    # gate (armed nodes only), dispatch = command settle on either
    # path (native bursts reuse the native_burst elapsed — no extra
    # clock read on the hot path), reply_write = one buffered write
    # flush to the transport.
    "pipeline.accept",
    "pipeline.read",
    "pipeline.parse",
    "pipeline.classify",
    "pipeline.dispatch",
    "pipeline.reply_write",
)

# Node-wide gauges (per-peer convergence lag lives on the Cluster and
# surfaces through SYSTEM LATENCY; only the folded node-wide values are
# registry gauges).
GAUGES = (
    "cluster.converge_lag_ms",
    "cluster.backlog_ms",
    # peers whose unacked delta gap fell off the retransmit window and
    # are owed a range repair (schema v8 anti-entropy); pinned at 0 by
    # the churn soak once every heal completes
    "cluster.interval_dirty_peers",
    # bridge failover (PR 15): 1 while this node is its region's
    # elected bridge (0 otherwise, and always 0 region-less), and the
    # live byte depth of the cross-bridge repair relay queue
    "cluster.bridge_is_self",
    "cluster.relay_queue_bytes",
    # overload armor (admission.py): the declared overload state (1
    # while shedding by class, 0 otherwise — hysteresis contract in
    # docs/operations.md) and the live total of un-drained reply bytes
    # the --admission-queue-bytes hard bound is enforced against
    "serving.overload",
    "serving.queued_bytes",
)
