"""Bounded structured trace ring.

A fixed-size deque of (ts_ms, subsystem, event, reason, detail) tuples
fed by the same seams the failpoints manifest names — connection
teardowns, dial failures, demotions, journal errors, rotations,
snapshot failures. Where a log line is gone once the stream scrolls,
the ring keeps the LAST `cap` structured events queryable from any
Redis client (`SYSTEM TRACE [count]`) and is dumped automatically on
unclean shutdown (main.py), so a post-mortem starts with the node's own
account of its final seconds.

Memory is bounded twice: `deque(maxlen=cap)` overwrites oldest-first,
and `detail` is truncated to DETAIL_CAP characters so one enormous
exception repr cannot balloon the ring. Appends are GIL-atomic
(deque.append), so events from worker threads interleave safely with
the event loop's.
"""

from __future__ import annotations

import time
from collections import deque

DEFAULT_CAP = 512
DETAIL_CAP = 200


def now_ms() -> int:
    return time.time_ns() // 1_000_000


class TraceRing:
    __slots__ = ("cap", "_ring")

    def __init__(self, cap: int = DEFAULT_CAP):
        self.cap = cap
        self._ring: deque = deque(maxlen=cap)

    def push(
        self, subsystem: str, event: str, reason: str = "", detail: str = ""
    ) -> None:
        detail = str(detail)
        if len(detail) > DETAIL_CAP:
            detail = detail[:DETAIL_CAP]
        self._ring.append((now_ms(), subsystem, event, str(reason), detail))

    def __len__(self) -> int:
        return len(self._ring)

    def dump(self, count: int | None = None) -> list[tuple]:
        """Chronological (oldest first); the newest `count` when given."""
        items = list(self._ring)
        if count is not None and count < len(items):
            items = items[len(items) - count :]
        return items

    @staticmethod
    def format(entry: tuple) -> str:
        ts, subsystem, event, reason, detail = entry
        out = f"{ts} {subsystem} {event}"
        if reason:
            out += f" {reason}"
        if detail:
            out += f" | {detail}"
        return out
