"""Opt-in Prometheus text-exposition endpoint (``--metrics-port``).

A scrape-friendly view of the same registry `SYSTEM METRICS` reads, so
the node is observable WITHOUT a Redis client: counters for commands
served / serving split / journal / cluster lifecycle, one summary per
latency seam (quantiles from the log2 histograms), and the node-wide
gauges. Format is the Prometheus text exposition (version 0.0.4);
`make ci`'s metrics-smoke step boots a node, scrapes this endpoint, and
validates both the grammar and that every histogram/gauge declared in
scripts/jlint/metrics_manifest.json is present from boot.

The server is a deliberately tiny asyncio HTTP responder (GET /metrics
only): a scrape every few seconds does not justify an HTTP framework
dependency, and the render itself is a pure function over the registry
(`render`), testable without sockets.
"""

from __future__ import annotations

import asyncio

from ..utils.net import ipv4_port
from .hist import N_BUCKETS, bucket_upper_seconds

# the `le` label per log2 bucket, precomputed once (bucket 0 is the
# exact-zero bucket; the last bucket is the clamp bucket and its upper
# bound is only nominal — +Inf carries the true total)
_LE_LABELS = tuple(
    f"{bucket_upper_seconds(i):.10g}" for i in range(N_BUCKETS)
)


def _esc(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def render(database) -> str:
    """The full exposition body for one node. ``database`` carries the
    registry plus the served/serving/cluster views RepoSYSTEM uses, so
    the scrape and SYSTEM METRICS can never disagree about sources."""
    reg = database.metrics
    system = database.system
    out: list[str] = []

    out.append("# HELP jylis_cmds_total Commands served per data type.")
    out.append("# TYPE jylis_cmds_total counter")
    served = system.served_fn() if system.served_fn else {}
    for name, n in sorted(served.items()):
        out.append(f'jylis_cmds_total{{type="{_esc(name)}"}} {n}')

    out.append("# TYPE jylis_serving_total counter")
    serving = system.serving_fn() if system.serving_fn else {}
    for key in ("native_cmds", "demoted_cmds", "demotions", "busy_refusals"):
        out.append(
            f'jylis_serving_total{{kind="{key}"}} {serving.get(key, 0)}'
        )

    overload = system.overload_fn() if system.overload_fn else {}
    if overload.get("armed"):
        # overload armor (admission.py): same split discipline as the
        # SESSION section — monotone transition/shed counters vs the
        # live state/pressure gauges — so rate() stays meaningful
        _OVERLOAD_GAUGES = ("state", "ewma_us", "inflight", "queued_bytes")
        out.append("# TYPE jylis_overload_total counter")
        for key, v in overload.items():
            if key not in _OVERLOAD_GAUGES and key != "armed":
                out.append(f'jylis_overload_total{{kind="{_esc(key)}"}} {v}')
        out.append("# TYPE jylis_overload gauge")
        for key in _OVERLOAD_GAUGES:
            if key in overload:
                out.append(f'jylis_overload{{key="{key}"}} {overload[key]}')

    session = system.session_fn() if system.session_fn else {}
    if session:
        # the section mixes monotone counters with two live gauges —
        # split the exposition so rate()/increase() stay meaningful
        _SESSION_GAUGES = ("origins", "parked_seqs")
        out.append("# TYPE jylis_session_total counter")
        for key, v in sorted(session.items()):
            if key not in _SESSION_GAUGES:
                out.append(f'jylis_session_total{{kind="{_esc(key)}"}} {v}')
        out.append("# TYPE jylis_session gauge")
        for key in _SESSION_GAUGES:
            if key in session:
                out.append(
                    f'jylis_session{{key="{_esc(key)}"}} {session[key]}'
                )

    out.append("# TYPE jylis_journal_total counter")
    for key, n in reg.journal_counters.items():
        out.append(f'jylis_journal_total{{kind="{key}"}} {n}')

    out.append("# TYPE jylis_drain_total counter")
    for name, drains, keys, ms in reg.type_stats():
        t = _esc(name)
        out.append(f'jylis_drain_total{{type="{t}",kind="batches"}} {drains}')
        out.append(f'jylis_drain_total{{type="{t}",kind="keys"}} {keys}')

    cluster = system.cluster_fn() if system.cluster_fn else {}
    if cluster:
        out.append("# TYPE jylis_cluster gauge")
        for key, v in cluster.items():
            out.append(f'jylis_cluster{{key="{_esc(key)}"}} {v}')

    out.append(
        "# HELP jylis_seam_latency_seconds Log2-bucket latency per "
        "instrumented seam."
    )
    out.append("# TYPE jylis_seam_latency_seconds summary")
    for name, snap in reg.seam_stats():
        seam = _esc(name)
        for q, key in (("0.5", "p50_s"), ("0.9", "p90_s"), ("0.99", "p99_s")):
            out.append(
                f'jylis_seam_latency_seconds{{seam="{seam}",quantile="{q}"}}'
                f" {snap[key]:.9f}"
            )
        out.append(
            f'jylis_seam_latency_seconds_count{{seam="{seam}"}} {snap["count"]}'
        )
        out.append(
            f'jylis_seam_latency_seconds_sum{{seam="{seam}"}} {snap["sum_s"]:.9f}'
        )

    # the same seams as REAL cumulative histograms (satellite of the
    # jtrace round): quantile gauges above are convenient but opaque to
    # PromQL — histogram_quantile()/Grafana need `_bucket` series, and
    # cumulative bucket counters sum correctly across lanes where a
    # quantile never does. Distinct family name: one family cannot be
    # both summary and histogram.
    out.append(
        "# HELP jylis_seam_latency_log2_seconds The same log2 seam "
        "histograms as cumulative Prometheus buckets."
    )
    out.append("# TYPE jylis_seam_latency_log2_seconds histogram")
    for name in reg.hists:
        seam = _esc(name)
        h = reg.hists[name]
        cum = 0
        for i, c in enumerate(h.buckets):
            cum += c
            out.append(
                f'jylis_seam_latency_log2_seconds_bucket{{seam="{seam}"'
                f',le="{_LE_LABELS[i]}"}} {cum}'
            )
        # +Inf and _count both use the bucket sum (not h.count) so the
        # family is self-consistent even mid-race with a recorder
        out.append(
            f'jylis_seam_latency_log2_seconds_bucket{{seam="{seam}"'
            f',le="+Inf"}} {cum}'
        )
        out.append(
            f'jylis_seam_latency_log2_seconds_count{{seam="{seam}"}} {cum}'
        )
        out.append(
            f'jylis_seam_latency_log2_seconds_sum{{seam="{seam}"}}'
            f" {h.total:.9f}"
        )

    # fleet convergence SLOs (obs/jtrace.py): the fraction of sampled
    # deltas fully applied within each --converge-slo-ms threshold,
    # plus the raw counters the lane aggregator re-derives node-wide
    # fractions from (fractions are not summable; counts are)
    out.append(
        "# HELP jylis_converge_slo Fraction of sampled deltas applied "
        "within le milliseconds end to end."
    )
    out.append("# TYPE jylis_converge_slo gauge")
    slo = reg.spans.slo_fracs()
    for ms, frac, _ in slo:
        out.append(f'jylis_converge_slo{{le="{ms}"}} {frac:.6f}')
    out.append("# TYPE jylis_converge_slo_total counter")
    out.append(
        f'jylis_converge_slo_total{{kind="sampled"}} {reg.spans.sampled}'
    )
    out.append(
        f'jylis_converge_slo_total{{kind="malformed"}} {reg.spans.malformed}'
    )
    for ms, _, ok in slo:
        out.append(f'jylis_converge_slo_total{{kind="ok_{ms}"}} {ok}')

    out.append("# HELP jylis_gauge Node-wide observability gauges.")
    out.append("# TYPE jylis_gauge gauge")
    for name, v in sorted(reg.gauges.items()):
        out.append(f'jylis_gauge{{name="{_esc(name)}"}} {v:.3f}')

    out.append(f"jylis_trace_events {len(reg.trace)}")
    # a scrape is a natural (rate-limited) deposit point for the
    # windowed-quantile marks SYSTEM LATENCY WINDOW subtracts against
    reg.window_deposit()
    return "\n".join(out) + "\n"


class MetricsHTTP:
    """GET /metrics on ``port`` (0 = ephemeral; the bound port is
    `.port`). Anything else is a 404; malformed requests just close.

    ``render_async`` swaps the body producer (an async () -> str): the
    lane supervisor's aggregated endpoint (lanes.py) reuses this whole
    responder — request parse, bounded header drain, status handling —
    with its own multi-lane render."""

    def __init__(self, database, port: int, log=None, render_async=None):
        self._database = database
        self._want_port = port
        self._log = log
        self._server: asyncio.base_events.Server | None = None
        self._render = render_async or self._render_default

    async def _render_default(self) -> str:
        return render(self._database)

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, host=None, port=self._want_port
        )

    @property
    def port(self) -> int:
        assert self._server is not None
        return ipv4_port(self._server)

    async def _handle(self, reader, writer) -> None:
        try:
            line = await asyncio.wait_for(reader.readline(), timeout=5.0)
            parts = line.split()
            # drain the (ignored) request headers so the client's write
            # half can complete cleanly before we respond — bounded, so
            # a client dripping header lines forever cannot hold this
            # handler task (and its socket) open indefinitely
            for _ in range(128):
                h = await asyncio.wait_for(reader.readline(), timeout=5.0)
                if h in (b"\r\n", b"\n", b""):
                    break
            else:
                return  # header flood: just close
            if len(parts) >= 2 and parts[0] == b"GET" and (
                parts[1] == b"/metrics" or parts[1].startswith(b"/metrics?")
            ):
                body = (await self._render()).encode()
                head = (
                    b"HTTP/1.1 200 OK\r\n"
                    b"Content-Type: text/plain; version=0.0.4; charset=utf-8\r\n"
                    b"Content-Length: %d\r\nConnection: close\r\n\r\n"
                    % len(body)
                )
                writer.write(head + body)
            else:
                writer.write(
                    b"HTTP/1.1 404 Not Found\r\nContent-Length: 0\r\n"
                    b"Connection: close\r\n\r\n"
                )
            await writer.drain()
        except (
            OSError,
            ValueError,  # readline: line longer than the stream limit
            asyncio.TimeoutError,
            asyncio.CancelledError,
        ):
            pass
        finally:
            writer.close()

    async def dispose(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
