"""Session guarantees: read-your-writes / monotonic-reads tokens.

The paper's store is eventually consistent: a client that writes on one
replica (or one serving lane) and reads on another can observe its own
write missing — fine for a single LAN socket, disqualifying for a
system serving one logical session across many replicas. This module
cashes in the schema-v8 delta-interval machinery for a client-visible
contract (the classic session-guarantee construction of Terry et al.,
"Session Guarantees for Weakly Consistent Replicated Data"):

* Every replica's cluster engine already runs a **per-sender monotone
  batch sequence** (``MsgSeqPush``): a sender's local writes are totally
  ordered by its seq counter, and a receiver knows exactly which prefix
  of each sender's stream it has applied.
* A **session token** is a compact vector of ``(origin rid, seq)``
  pairs: "the writes this session depends on are covered by these
  senders' streams up to these seqs". ``SESSION TOKEN`` / ``SESSION
  WRAP`` mint one after forcing the pending local deltas through the
  flush path, so the client's own writes are sequenced before the
  vector is read.
* A read presenting a token (``SESSION READ``) is served once the local
  **applied-interval vector** (:class:`SessionIndex`) dominates the
  token — bounded wait (``--session-wait-ms``), then a typed ``STALE``
  refusal. The reply carries the join of the token and the server's
  vector, which is what makes successive reads monotonic.

The applied vector is deliberately STRICTER than the transport's
``_recv_cum`` cursors: ``_track_seq`` baselines at the first observed
seq (history arrives via the digest-tree bootstrap sync, which is fine
for lattice convergence), but a session vector that jumped to a
first-observed seq would claim writes 1..seq-1 visible when they are
not — a real read-your-writes violation, and exactly the deliberately
broken variant jmodel minimizes a counterexample for
(``session_unsafe``). Here a per-origin watermark advances only by
**contiguous application from zero** (or from an adopted base), with a
bounded out-of-order park; everything else waits for **digest-match
adoption**: a sync digest match proves byte-equal state, so the peer's
whole vector folds in (``MsgSyncRequest``/``MsgSyncDone`` carry it both
ways). Adoption is also what heals a rebooted origin: its seq counter
restarts, so each boot mints a fresh rid (address + boot epoch) and the
old incarnation's entries survive on peers, frozen and adoptable.

Tokens survive a client bouncing across lanes because the lane bus IS a
cluster (each lane's vector tracks its siblings' bus streams), and
across replicas/regions because bridges relay foreign streams with
origin attribution preserved (``MsgRelayPush``). docs/sessions.md has
the token format, the guarantee matrix, and the STALE/BUSY contracts.
"""

from __future__ import annotations

import asyncio
import struct
import zlib

U64_MAX = (1 << 64) - 1

# wire format version byte of the token itself (not the cluster schema:
# tokens live in CLIENT hands across node upgrades, so they carry their
# own version and a CRC — a mangled or truncated token must be a typed
# BADTOKEN refusal, never a misread vector)
TOKEN_VERSION = 1
# decode-side bounds: a token is a per-origin vector, so its entry count
# is bounded by cluster size x retained epochs — 4096 is generous, and
# the cap stops a hostile client making the server allocate per junk byte
TOKEN_MAX_ENTRIES = 4096
TOKEN_MAX_RID = 512  # rid = "host:port:name!epoch" — far under this

# per-origin out-of-order park (seqs above the contiguity watermark,
# waiting for the gap): bounded like the transport's RECV_OOO_CAP; past
# the cap the lowest parked seqs drop — they re-enter via digest-match
# adoption, never via a watermark jump
PARK_CAP = 512
# retained (addr, epoch) incarnations per address: older epochs' entries
# are frozen-but-valid (their writes were applied); keeping a few lets
# pre-reboot tokens verify, pruning the tail bounds vector growth
EPOCHS_PER_ADDR = 4

SESSION_WAIT_MS_DEFAULT = 500


class SessionError(Exception):
    """Token decode failure — surfaces as the BADTOKEN refusal."""


def make_rid(addr: str, epoch: int) -> str:
    """One origin incarnation: advertised address + boot epoch. The
    epoch (boot wall-ms through the cluster's injectable clock) is what
    keeps a rebooted origin's restarted seq counter from aliasing its
    previous stream in every peer's vector."""
    return f"{addr}!{epoch}"


def rid_addr(rid: str) -> str:
    """The address part of a rid (epoch pruning groups by this)."""
    return rid.rsplit("!", 1)[0]


def encode_token(vec: dict[str, int]) -> bytes:
    """version u8, entry count varint, per entry (rid:str seq:varint)
    sorted by rid, then crc32 over everything before it (u32be). An
    empty vector is a legal token (it dominates trivially — the null
    session)."""
    out = bytearray((TOKEN_VERSION,))
    _w_varint(out, len(vec))
    for rid in sorted(vec):
        rb = rid.encode()
        _w_varint(out, len(rb))
        out += rb
        _w_varint(out, vec[rid])
    out += struct.pack(">I", zlib.crc32(bytes(out)))
    return bytes(out)


def decode_token(data: bytes) -> dict[str, int]:
    """Inverse of encode_token; every malformation — truncation at any
    byte, CRC mismatch, u64 overflow, duplicate rid, trailing bytes —
    raises :class:`SessionError`."""
    if len(data) < 1 + 1 + 4:
        raise SessionError("token too short")
    body, crc_bytes = data[:-4], data[-4:]
    if struct.unpack(">I", crc_bytes)[0] != zlib.crc32(body):
        raise SessionError("token crc mismatch")
    if body[0] != TOKEN_VERSION:
        raise SessionError(f"unknown token version {body[0]}")
    pos = 1
    count, pos = _r_varint(body, pos)
    if count > TOKEN_MAX_ENTRIES:
        raise SessionError("token entry count out of bounds")
    vec: dict[str, int] = {}
    for _ in range(count):
        rlen, pos = _r_varint(body, pos)
        if rlen > TOKEN_MAX_RID or pos + rlen > len(body):
            raise SessionError("token rid out of bounds")
        try:
            rid = body[pos : pos + rlen].decode()
        except UnicodeDecodeError as e:
            raise SessionError("token rid not utf-8") from e
        pos += rlen
        seq, pos = _r_varint(body, pos)
        if seq > U64_MAX:
            raise SessionError("token seq exceeds u64")
        if rid in vec:
            raise SessionError("duplicate token rid")
        vec[rid] = seq
    if pos != len(body):
        raise SessionError("trailing bytes after token")
    return vec


def dominates(vec: dict[str, int], token: dict[str, int]) -> bool:
    """True when the applied vector covers every token entry."""
    return all(vec.get(rid, 0) >= seq for rid, seq in token.items())


# decoded-token memo (per process): clients re-present the same token
# bytes on every read of a session, so the serving path pays the full
# decode+CRC once per distinct token instead of once per command.
# Bounded by wholesale clear; values are treated as immutable by every
# caller (declared in scripts/jlint/lanes_manifest.json — a pure
# derived-data cache, so per-lane copies are trivially correct).
_DECODE_MEMO: dict[bytes, dict[str, int]] = {}
_DECODE_MEMO_CAP = 128


def decode_token_memo(data: bytes) -> dict[str, int]:
    """decode_token with the serving-path memo; the returned dict is
    SHARED — callers must not mutate it."""
    vec = _DECODE_MEMO.get(data)
    if vec is None:
        vec = decode_token(data)
        if len(_DECODE_MEMO) >= _DECODE_MEMO_CAP:
            _DECODE_MEMO.clear()
        _DECODE_MEMO[bytes(data)] = vec
    return vec


def join_vec(a: dict[str, int], b: dict[str, int]) -> dict[str, int]:
    out = dict(a)
    for rid, seq in b.items():
        if seq > out.get(rid, 0):
            out[rid] = seq
    return out


def _w_varint(out: bytearray, v: int) -> None:
    if v < 0:
        raise SessionError(f"negative varint: {v}")
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _r_varint(data: bytes, pos: int) -> tuple[int, int]:
    v = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise SessionError("truncated varint")
        byte = data[pos]
        pos += 1
        v |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return v, pos
        shift += 7
        if shift > 70:
            raise SessionError("varint too long")


class SessionIndex:
    """One node's (or lane's) applied-interval vector + waiter queue.

    Owned by the Database; fed by the cluster engine: ``note_local``
    after every flush that sequenced own batches, ``note_applied`` after
    every sequenced (direct or relayed) batch converges, ``adopt`` on
    every digest-match proof. ``unsafe`` arms the deliberately broken
    watermark rule (first-observed jump) for jmodel's counterexample
    demonstration — never set in production wiring."""

    def __init__(self, unsafe: bool = False):
        self.unsafe = unsafe
        self.srid: str | None = None  # set by the driving cluster's bind
        # async callable that forces the pending local deltas through
        # the cluster flush path (Cluster.flush_now); None on a node
        # with no cluster — tokens then carry whatever is verified
        self.flush_fn = None
        self._vec: dict[str, int] = {}
        self._parked: dict[str, list[int]] = {}
        self._waiters: list[asyncio.Future] = []
        self._tok_cache: bytes | None = None  # encode_token(_vec) memo
        self.stats = {
            "tokens_minted": 0,
            "reads_served": 0,
            "reads_waited": 0,
            "stale_refusals": 0,
            "badtoken_refusals": 0,
            "adoptions": 0,
            "parked_dropped": 0,
        }

    # ---- vector advance paths ---------------------------------------------

    def bind(self, srid: str, flush_fn) -> None:
        """Wired by the DRIVING cluster instance (the one whose
        heartbeat drains the database): its rid is the self entry every
        minted token leads with."""
        self.srid = srid
        self.flush_fn = flush_fn

    def note_local(self, srid: str, seq: int) -> None:
        """Own flushes: every local write up to the just-assigned seq is
        in the own stream by construction — unconditional max."""
        if seq > self._vec.get(srid, 0):
            self._vec[srid] = seq
            self._wake()

    def note_applied(self, origin: str, seq: int) -> bool:
        """One sequenced batch of ``origin``'s stream has CONVERGED
        here (call after the converge completes, never before — a
        waiter woken between would serve a read the data hasn't
        reached). Returns True when the batch was first-sight (the
        bridge relay predicate); duplicates return False."""
        cum = self._vec.get(origin, 0)
        if seq <= cum:
            return False
        if self.unsafe:
            # the BROKEN rule (jmodel's counterexample target): adopt
            # any observed seq as the watermark — claims writes
            # 1..seq-1 visible without evidence
            self._vec[origin] = seq
            self._wake()
            return True
        parked = self._parked.get(origin)
        if seq == cum + 1:
            cum += 1
            if parked:
                parked.sort()
                while parked and parked[0] == cum + 1:
                    cum += 1
                    parked.pop(0)
                if not parked:
                    del self._parked[origin]
            self._vec[origin] = cum
            self._wake()
            return True
        if parked is None:
            parked = self._parked[origin] = []
        if seq in parked:
            return False
        parked.append(seq)
        if len(parked) > PARK_CAP:
            # the gap is not filling through this path: drop the LOWEST
            # parked seqs (the watermark can only reach them via
            # adoption now anyway) — bounded memory, never a jump
            parked.sort()
            drop = len(parked) - PARK_CAP
            del parked[:drop]
            self.stats["parked_dropped"] += drop
        return True

    def adopt(self, vec: dict[str, int]) -> None:
        """Digest-match proof: the peer's state equals ours, so every
        write its vector covers is in our state — pointwise max fold,
        then collapse any parked seqs the new watermarks subsume."""
        if not vec:
            return
        changed = False
        for rid, seq in vec.items():
            if seq > U64_MAX:
                continue  # never let a hostile peer poison the vector
            if seq > self._vec.get(rid, 0):
                self._vec[rid] = seq
                changed = True
        if changed:
            self.stats["adoptions"] += 1
            for origin in list(self._parked):
                cur = self._vec.get(origin, 0)
                cum = cur
                parked = sorted(s for s in self._parked[origin] if s > cum)
                while parked and parked[0] == cum + 1:
                    cum += 1
                    parked.pop(0)
                if cum > cur:
                    # only when the collapse actually advanced: an
                    # unconditional write would mint phantom 0-seq
                    # entries for origins that have ONLY parked seqs
                    # (review find)
                    self._vec[origin] = cum
                if parked:
                    self._parked[origin] = parked
                else:
                    del self._parked[origin]
            self._prune()
            self._wake()

    def _prune(self) -> None:
        """Keep the newest EPOCHS_PER_ADDR incarnations per address;
        pruning only ever makes dominance stricter (STALE, never a
        false serve)."""
        by_addr: dict[str, list[str]] = {}
        for rid in self._vec:
            by_addr.setdefault(rid_addr(rid), []).append(rid)
        for addr, rids in by_addr.items():
            if len(rids) <= EPOCHS_PER_ADDR:
                continue
            rids.sort(key=_rid_epoch)
            for rid in rids[: len(rids) - EPOCHS_PER_ADDR]:
                if rid != self.srid:
                    del self._vec[rid]
                    self._parked.pop(rid, None)

    # ---- the read side -----------------------------------------------------

    def vector(self) -> dict[str, int]:
        return dict(self._vec)

    def token_bytes(self) -> bytes:
        """The vector as encoded token bytes, memoised per advance —
        the common reply token: a SERVED read's join(token, vec) IS vec
        (the serve condition is exactly vec >= token), and minting
        after a no-op flush re-reads the same vector."""
        if self._tok_cache is None:
            self._tok_cache = encode_token(self._vec)
        return self._tok_cache

    def dominated(self, token: dict[str, int]) -> bool:
        return dominates(self._vec, token)

    async def wait_dominated(self, token: dict[str, int], wait_ms: int) -> bool:
        """Bounded wait for the applied vector to dominate ``token``;
        True = serve, False = the STALE refusal. Wakes on every vector
        advance (local flush, converge, adoption)."""
        if self.dominated(token):
            return True
        self.stats["reads_waited"] += 1
        loop = asyncio.get_running_loop()
        deadline = loop.time() + wait_ms / 1e3
        while True:
            remaining = deadline - loop.time()
            if remaining <= 0:
                return self.dominated(token)
            fut = loop.create_future()
            self._waiters.append(fut)
            try:
                await asyncio.wait_for(asyncio.shield(fut), remaining)
            except asyncio.TimeoutError:
                pass
            finally:
                if not fut.done():
                    fut.cancel()
                if fut in self._waiters:
                    self._waiters.remove(fut)
            if self.dominated(token):
                return True

    def _wake(self) -> None:
        self._tok_cache = None  # every wake is a vector change
        waiters, self._waiters = self._waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(None)

    # ---- observability -----------------------------------------------------

    def metrics_totals(self) -> dict[str, int]:
        """The SYSTEM METRICS `SESSION` section (docs/operations.md
        glossary)."""
        out = dict(self.stats)
        out["origins"] = len(self._vec)
        out["parked_seqs"] = sum(len(p) for p in self._parked.values())
        return out

    def canonical(self):
        """Protocol-relevant state for jmodel's state hash."""
        return (
            sorted(self._vec.items()),
            sorted((o, tuple(sorted(p))) for o, p in self._parked.items()),
        )


def _rid_epoch(rid: str) -> int:
    tail = rid.rsplit("!", 1)
    try:
        return int(tail[1]) if len(tail) == 2 else 0
    except ValueError:
        return 0
