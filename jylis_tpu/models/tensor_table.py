"""Host-state backend for the TENSOR repo.

The counter_table.py / treg_table.py pattern, pure-Python only: TENSOR
commands are served by the Python oracle path (the native engine
defers any first word it does not know), so there is no native view to
mirror — the table IS the host truth. Every cell is an
ops/tensor_host.Tensor; the serving winner is the join of the drained
cache and the pending window, so a drain never changes what GET
observes (``fold_pend`` just moves the window into the cache) — the
"observe-first" posture: reads observe host state, only writes
schedule device work.
"""

from __future__ import annotations

from ..ops.tensor_host import Tensor


def _joined(a: Tensor | None, b: Tensor | None) -> Tensor | None:
    """Always a FRESH Tensor: winners escape the table into sync canons,
    snapshot dumps, and cluster sync-dump encodes that run in worker
    threads after the repo lock is released — the live cache/pending
    objects must never alias out, or a concurrent drain's in-place
    converge corrupts the bytes mid-encode."""
    if a is None and b is None:
        return None
    out = Tensor()
    if a is not None:
        out.converge(a)
    if b is not None:
        out.converge(b)
    return out


class PyTensorTable:
    __slots__ = ("_keys", "_rkeys", "_cache", "_pending", "_deltas",
                 "_sync_dirty")

    def __init__(self):
        self._keys: dict[bytes, int] = {}
        self._rkeys: list[bytes] = []
        self._cache: dict[int, Tensor] = {}  # drained winner
        self._pending: dict[int, Tensor] = {}  # joined since last drain
        self._deltas: dict[int, Tensor] = {}  # joined since last flush
        self._sync_dirty: dict[int, None] = {}  # since last digest pass

    def rows(self) -> int:
        return len(self._rkeys)

    def upsert(self, key: bytes) -> int:
        row = self._keys.get(key)
        if row is None:
            row = len(self._rkeys)
            self._keys[key] = row
            self._rkeys.append(key)
        return row

    def find(self, key: bytes) -> int:
        return self._keys.get(key, -1)

    def key_of(self, row: int) -> bytes:
        return self._rkeys[row]

    def stamp(self, row: int) -> tuple[int, int] | None:
        """(mode, dim) of the row's winner — the RESP boundary's
        mismatch check reads this before admitting a write."""
        w = self.winner(row)
        return None if w is None or w.mode == 0 else (w.mode, w.dim)

    def write(self, row: int, delta: Tensor) -> None:
        self._sync_dirty[row] = None
        cur = self._pending.get(row)
        if cur is None:
            cur = Tensor()
            self._pending[row] = cur
        cur.converge(delta)

    def note_delta(self, row: int, delta: Tensor) -> None:
        cur = self._deltas.get(row)
        if cur is None:
            cur = Tensor()
            self._deltas[row] = cur
        cur.converge(delta)

    def winner(self, row: int) -> Tensor | None:
        return _joined(self._cache.get(row), self._pending.get(row))

    def pend_count(self) -> int:
        return len(self._pending)

    def export_pend(self) -> list[tuple[int, Tensor]]:
        return list(self._pending.items())

    def fold_pend(self) -> None:
        for row, p in self._pending.items():
            c = self._cache.get(row)
            if c is None:
                c = Tensor()
                self._cache[row] = c
            c.converge(p)
        self._pending.clear()

    def deltas_size(self) -> int:
        return len(self._deltas)

    def flush_deltas(self):
        out = sorted(
            (self._rkeys[row], t) for row, t in self._deltas.items()
        )
        self._deltas.clear()
        return out

    def dump(self):
        out = []
        for key, row in sorted(self._keys.items()):
            w = self.winner(row)
            if w is not None and w.mode != 0:
                out.append((key, w))
        return out

    def export_sync_dirty(self) -> list[int]:
        rows = list(self._sync_dirty)
        self._sync_dirty.clear()
        return rows
