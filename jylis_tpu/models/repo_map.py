"""MAP repo: a generic key -> (field -> registered lattice) keyspace.

ROADMAP item 4's first half. No reference analog — jylis has no
composite type; the design frame is arXiv:2004.04303 (lattice
composition) + arXiv:1605.06424 (decomposed deltas). The value
semantics live in ops/compose.py; this repo is the vertical-slice
glue: RESP surface, decomposed per-field delta flushes, converge
buffering with a timed host drain, (key, field)-granular digest
entries, and snapshot dump/load.

RESP surface (``MAP <TYPE> <OP> …``, TYPE = any registered inner
lattice — TREG, TLOG, GCOUNT, PNCOUNT):

    MAP <TYPE> SET key field <inner write args…>
    MAP <TYPE> GET key field
    MAP <TYPE> DEL key field
    MAP <TYPE> KEYS key

Delta wire shape: ``(packed(key, field), (itype, ver, tomb, val))`` —
one FIELD's full product state per entry (self-justifying under join;
the inner val uses the inner type's own delta encoding, recursively —
schema v9). One field edit ships one field, never the map; a DEL ships
a tombstone-only unit (ver empty, val = inner bottom). The digest tree
hashes packed (key, field) leaves, so Merkle-range repair pulls
divergent FIELDS.
"""

from __future__ import annotations

from ..ops.compose import REGISTRY, pack_field, unpack_field
from ..utils.metrics import timed_drain
from .base import ParseError, need
from .help import RepoHelp
from .map_table import PyMapTable

MAP_HELP = RepoHelp(
    "MAP",
    {
        "SET": "type key field ...  (inner write args, e.g. TREG: value ts)",
        "GET": "type key field",
        "DEL": "type key field",
        "KEYS": "type key",
    },
)

# foreign units buffered past this fold in a worker thread off the
# serving loop (the host analog of the device repos' drain thresholds)
PENDING_DRAIN_THRESHOLD = 512


class RepoMAP:
    name = "MAP"
    help = MAP_HELP

    def __init__(self, identity: int, engine=None, **_kw):
        # engine accepted for constructor parity; MAP is python-only
        # (the native engine defers unknown first words to the oracle)
        self._identity = identity
        self._tbl = PyMapTable()
        # wire units dropped at the converge boundary (malformed
        # composite key from a peer): nothing joinable to keep, but the
        # count stays visible to tests/debugging
        self._dropped_units = 0

    # -- commands ------------------------------------------------------------

    def apply(self, resp, args: list[bytes]) -> bool:
        itype_b = need(args, 0)
        op = need(args, 1)
        itype = itype_b.decode("ascii", "replace")
        inner = REGISTRY.get(itype)
        if inner is None:
            raise ParseError()
        if op == b"GET":
            if self._tbl.pending:
                self.drain()
            key, field = need(args, 2), need(args, 3)
            m = self._tbl.find(key)
            val = m.get_field(field, itype) if m is not None else None
            if val is None:
                resp.null()
            else:
                inner.render(resp, val)
            return False
        if op == b"KEYS":
            if self._tbl.pending:
                self.drain()
            key = need(args, 2)
            m = self._tbl.find(key)
            fields = m.live_fields(itype) if m is not None else []
            resp.array_start(len(fields))
            for f in fields:
                resp.string(f)
            return False
        if op == b"SET":
            key, field = need(args, 2), need(args, 3)
            if self._tbl.pending:
                # local edit counters must advance past everything this
                # replica has OBSERVED, including buffered foreign units
                self.drain()
            try:
                self._tbl.map_for(key).set_field(
                    field, self._identity, itype, args[4:]
                )
            except ValueError:
                raise ParseError() from None
            self._tbl.note_edit(key, field)
            resp.ok()
            return True
        if op == b"DEL":
            key, field = need(args, 2), need(args, 3)
            if self._tbl.pending:
                # observed-remove: the tombstone must cover the edits
                # this replica has seen — fold them in first
                self.drain()
            m = self._tbl.find(key)
            unit = m.del_field(field, self._identity) if m is not None else None
            resp.ok()
            if unit is None:
                return False  # unknown/dead field: nothing to remove
            self._tbl.note_edit(key, field)
            return True
        raise ParseError()

    # -- lattice plumbing ----------------------------------------------------

    def converge(self, key: bytes, delta: tuple) -> None:
        # key is the PACKED (key, field) composite; buffer only — the
        # serving path drains via drain_overdue in a worker thread.
        # Validate the composite SHAPE eagerly: the codec treats batch
        # keys as opaque bytes, so a buggy peer can ship a key no
        # unpack can parse — buffered unvalidated, it would blow up the
        # fold mid-drain and take every other buffered unit with it.
        # A key that names no (key, field) carries nothing joinable:
        # drop it here, alone.
        try:
            unpack_field(key)
        except ValueError:
            self._dropped_units += 1
            return
        self._tbl.buffer_unit(key, delta)

    def drain_overdue(self) -> bool:
        return len(self._tbl.pending) >= PENDING_DRAIN_THRESHOLD

    @timed_drain("MAP", lambda self: len(self._tbl.pending))
    def drain(self) -> None:
        self._tbl.fold_pending()

    def deltas_size(self) -> int:
        return len(self._tbl.dirty)

    def flush_deltas(self):
        if self._tbl.pending:
            self.drain()
        out = []
        for packed in self._tbl.export_dirty():
            unit = self._tbl.field_unit(packed)
            if unit is not None:
                out.append((packed, unit))
        return out

    # -- sync digest (models/database.py incremental tree) -------------------

    def sync_prepare(self) -> None:
        if self._tbl.pending:
            self.drain()

    def sync_dirty_keys(self) -> list[bytes]:
        return self._tbl.export_sync_dirty()

    def sync_canon(self, key: bytes) -> bytes | None:
        canon = self._tbl.field_canon(key)
        return None if canon is None else repr(canon).encode()

    # -- snapshot (persist.py): full state in the wire-delta shape ----------

    def dump_state(self):
        if self._tbl.pending:
            self.drain()
        out = []
        for packed in self._tbl.all_packed():
            unit = self._tbl.field_unit(packed)
            if unit is not None:
                out.append((packed, unit))
        return out

    def load_state(self, batch) -> None:
        for packed, unit in batch:
            self.converge(packed, unit)
        self.drain()

    # -- direct host views (tests / bench) -----------------------------------

    def get_value(self, key: bytes, field: bytes, itype: str):
        if self._tbl.pending:
            self.drain()
        m = self._tbl.find(key)
        return m.get_field(field, itype) if m is not None else None


def unpack_wire_key(packed: bytes) -> tuple[bytes, bytes]:
    """Re-exported for operators/tests reading journal or range frames."""
    return unpack_field(packed)


__all__ = ["RepoMAP", "MAP_HELP", "pack_field", "unpack_wire_key"]
