"""Command-help rendering on parse failure.

Reference behavior: help.pony:4-44 — every unparseable command gets an
error reply of "BADCOMMAND (could not parse command)" followed by either
the usage form of the named operation or the full operation table of the
data type; database.pony:28-39 renders the data-type list for an unknown
first word.
"""

from __future__ import annotations

BADCOMMAND_PREFIX = "BADCOMMAND (could not parse command)\n"


def respond_help(resp, help_text: str) -> None:
    resp.err(BADCOMMAND_PREFIX + help_text.rstrip())


class RepoHelp:
    """Operation table for one data type; renders per-op usage or the full
    table (help.pony:13-44)."""

    def __init__(self, datatype: str, commands: dict[str, str]):
        self.datatype = datatype
        self.commands = commands

    def render(self, cmd_after_type: list[bytes]) -> str:
        op = cmd_after_type[0].decode("utf-8", "replace") if cmd_after_type else None
        if op is not None and op in self.commands:
            return (
                "This operation expects the arguments in the following form:\n"
                f"{self.datatype} {op} {self.commands[op]}"
            )
        lines = [
            f"{self.datatype} {o} {args}" for o, args in self.commands.items()
        ]
        return (
            "The following are valid operations for this data type:\n"
            + "\n".join(lines)
        )


class LeafHelp:
    """Fixed help text (the SYSTEM repo's style, repo_system.pony:6-11)."""

    def __init__(self, text: str):
        self.text = text

    def render(self, cmd_after_type: list[bytes]) -> str:
        return self.text


DATATYPE_HELP = """\
The first word of each command must be a data type.
The following are valid data types (case sensitive):
  TREG    - Timestamped Register (Latest Write Wins)
  TLOG    - Timestamped Log (Retain Latest Entries)
  GCOUNT  - Grow-Only Counter
  PNCOUNT - Positive/Negative Counter
  UJSON   - Unordered JSON (Nested Observed-Remove Maps and Sets)
  TENSOR  - Tensor Register (Per-Coordinate Convergent Merges)
  MAP     - Composed Map (Fields Holding Any Registered Lattice)
  BCOUNT  - Bounded Counter (Replica-Local Escrow, value <= bound)
  SYSTEM  - (miscellaneous system-level operations)
"""
