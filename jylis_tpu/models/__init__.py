"""Data-type repos and the database router.

Reference analog: the L3/L4 layers (SURVEY.md sections 2.2-2.3) —
jylis/repo_*.pony and database.pony — re-designed for the host/device
split: each repo keeps authoritative lattice state in device tensors
(ops/), buffers mutations and incoming deltas into coalesced pending
batches, and drains them as single fused XLA calls that also return the
touched rows' serving values into a host cache, so reads are host dict
lookups and the device sees only large batches.
"""

from .database import Database  # noqa: F401
from .manager import RepoManager  # noqa: F401
from .repo_counters import RepoGCOUNT, RepoPNCOUNT  # noqa: F401
from .repo_treg import RepoTREG  # noqa: F401
from .repo_tlog import RepoTLOG  # noqa: F401
from .repo_ujson import RepoUJSON  # noqa: F401
from .repo_system import RepoSYSTEM  # noqa: F401
