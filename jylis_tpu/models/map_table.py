"""Host table backend for the MAP repo.

The flat types split host bookkeeping into table backends with a
pure-Python oracle and a native C++ twin (counter_table.py,
treg_table.py). MAP is host-only (python_only in the parity manifest,
like TENSOR), so there is ONE backend — but the split is kept so the
repo stays the thin RESP/flush/converge glue and a native twin can
slot in later without touching it.

State model: ``key -> ops.compose.MapCRDT`` (field -> product-lattice
Field). Three kinds of dirtiness are tracked at FIELD granularity,
keyed by the packed composite wire key (compose.pack_field):

* ``dirty``       — fields edited locally since the last delta flush
                    (what flush_deltas exports: decomposed per-field
                    units, never the map).
* ``sync_dirty``  — fields changed since the last digest fold (what
                    the incremental Merkle tree consumes: leaves hash
                    (key, field) pairs, so range repair pulls fields).
* ``pending``     — foreign units buffered by converge until the next
                    drain (the host analog of the device repos'
                    coalesced delta window; drain is the timed seam).
"""

from __future__ import annotations

from ..ops.compose import MapCRDT, pack_field, unpack_field


class PyMapTable:
    def __init__(self):
        self.maps: dict[bytes, MapCRDT] = {}
        self.dirty: set[bytes] = set()
        self.sync_dirty: set[bytes] = set()
        self.pending: list[tuple[bytes, tuple]] = []

    def map_for(self, key: bytes) -> MapCRDT:
        m = self.maps.get(key)
        if m is None:
            m = MapCRDT()
            self.maps[key] = m
        return m

    def find(self, key: bytes) -> MapCRDT | None:
        return self.maps.get(key)

    def note_edit(self, key: bytes, field: bytes) -> None:
        packed = pack_field(key, field)
        self.dirty.add(packed)
        self.sync_dirty.add(packed)

    def buffer_unit(self, packed: bytes, unit: tuple) -> None:
        self.pending.append((packed, unit))

    def fold_pending(self) -> None:
        """Apply the buffered foreign units (the drain body). Per-unit
        tolerance: the repo validates composite keys at the converge
        boundary, but a malformed unit reaching here anyway (a direct
        load path, a future regression) must drop ALONE — the swap
        above already emptied the buffer, so one raise would discard
        every unit buffered behind it."""
        pending, self.pending = self.pending, []
        for packed, unit in pending:
            try:
                key, field = unpack_field(packed)
                self.map_for(key).converge_field(field, unit)
            except (ValueError, KeyError):
                continue
            self.sync_dirty.add(packed)

    def export_dirty(self) -> list[bytes]:
        out = sorted(self.dirty)
        self.dirty.clear()
        return out

    def export_sync_dirty(self) -> list[bytes]:
        out = sorted(self.sync_dirty)
        self.sync_dirty.clear()
        return out

    def field_unit(self, packed: bytes) -> tuple | None:
        """The FULL current unit of one field (a fresh copy — callers
        alias it into journal/broadcast sinks), or None if unknown."""
        key, field = unpack_field(packed)
        m = self.maps.get(key)
        if m is None:
            return None
        f = m.fields.get(field)
        return None if f is None else f.unit()

    def field_canon(self, packed: bytes) -> tuple | None:
        """Canonical state of one field — tombstoned fields INCLUDED
        (a replica that saw a DEL and one that did not must digest
        apart until the tombstone syncs)."""
        key, field = unpack_field(packed)
        m = self.maps.get(key)
        if m is None:
            return None
        f = m.fields.get(field)
        return None if f is None else f.canon()

    def all_packed(self) -> list[bytes]:
        return sorted(
            pack_field(key, field)
            for key, m in self.maps.items()
            for field in m.fields
        )
