"""TREG repo: device-resident last-writer-wins register keyspace.

Reference analog: repo_treg.pony:11-68 (Map[key -> TRegString], per-key
converge loop). Here the keyspace is the ops/treg struct-of-arrays; local
SETs and incoming deltas coalesce host-side per key (exact LWW compare with
full strings — the host has them), then drain in one fused
compare-and-scatter call whose gathered results feed the host serving
cache. Rank-prefix ties that the device cannot settle (flagged rows) are
resolved here with full strings and patched with a tiny follow-up scatter.

Host bookkeeping (keys, winner, pending window, delta accumulator) lives
behind the table backends in treg_table.py: pure-Python dicts as the
oracle, or the native C++ engine — the SAME state the server's native
batch applier (native/serve_engine.cpp) mutates, so SETs applied natively
and Python-side drains/flushes share one source of truth. GET never pays
a device round-trip: the winner is an O(1) host compare.

Delta wire shape: (value: bytes, ts: u64).
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np

from ..native.engine import resolve_engine
from ..ops import planes, treg
from ..ops.interner import Interner, prefix_rank
from ..parallel import (
    drain_sharded_treg,
    patch_sharded_treg,
    route_drain,
    serving_mesh,
    shard_vec,
)
from .base import ParseError, bucket, need, pad_rows, parse_u64
from .treg_table import NativeTregTable, PyTregTable
from ..utils.metrics import timed_drain
from .help import RepoHelp

TREG_HELP = RepoHelp("TREG", {"GET": "key", "SET": "key value timestamp"})

# pending writes/deltas flush to the device once they pile this high:
# reads never need the drain (GET computes the winner host-side), so this
# bounds host memory while keeping device batches large.
# native/serve_engine.cpp TREG_PENDING_DRAIN must match.
PENDING_DRAIN_THRESHOLD = 4096

# interner compaction: once the table holds this many more ids than live
# registers, rebuild it from the live set (ops/interner.compact) so value
# churn can't grow host memory without bound
COMPACT_SLACK = 4096


@partial(jax.jit, donate_argnums=0)
def _drain(state, ki, ts_hi, ts_lo, rank_hi, rank_lo, vid):
    st, tie = treg.converge_batch(state, ki, ts_hi, ts_lo, rank_hi, rank_lo, vid)
    return st, tie, st.ts_hi[ki], st.ts_lo[ki], st.vid[ki]


@partial(jax.jit, donate_argnums=0)
def _drain_dense(state, ts_hi, ts_lo, rank_hi, rank_lo, vid):
    st, tie = treg.converge_dense(state, ts_hi, ts_lo, rank_hi, rank_lo, vid)
    return st, tie, st.ts_hi, st.ts_lo, st.vid


@partial(jax.jit, donate_argnums=0)
def _patch_vids(state, ki, vids):
    return state._replace(vid=state.vid.at[ki].set(vids, mode="drop"))


# a batch covering >= 1/DENSE_FRACTION of the keyspace drains through the
# elementwise dense join (each plane streamed once, no random access)
DENSE_FRACTION = 4


class RepoTREG:
    name = "TREG"
    help = TREG_HELP

    def __init__(
        self, identity: int, key_cap: int = 1024, mesh="auto", engine="auto"
    ):
        # identity is ignored: LWW needs no replica identity (repo_treg.pony:15)
        # mesh mode mirrors the counter repos (repo_counters.py): with >1
        # visible device the five planes live keys-sharded and drains
        # route through parallel/sharded.drain_sharded_treg
        self._mesh = serving_mesh() if mesh == "auto" else mesh
        self._n_shards = self._mesh.devices.size if self._mesh is not None else 1
        self._key_cap = self._round_cap(key_cap)
        self._state = self._place(treg.init(self._key_cap))
        self._interner = Interner()
        self._cache: dict[int, tuple[int, int]] = {}  # row -> (ts, vid)
        self.engine = engine = resolve_engine(engine)
        self._tbl = (
            NativeTregTable(engine) if engine is not None else PyTregTable()
        )

    def _round_cap(self, k: int) -> int:
        ns = self._n_shards
        return -(-k // ns) * ns

    def _place(self, state):
        if self._mesh is None:
            return state
        return type(state)(*(shard_vec(self._mesh, p) for p in state))

    # -- commands (repo_treg.pony:24-68) -----------------------------------

    def apply(self, resp, args: list[bytes]) -> bool:
        op = need(args, 0)
        if op == b"GET":
            # LWW winner = join(drained cache, un-drained pending) by the
            # exact (ts, value) rule — an O(1) host compare, so a GET
            # NEVER pays a device round-trip (the counters' host-shadow
            # posture; drains happen on write thresholds and snapshots)
            row = self._tbl.find(need(args, 1))
            cand = self._tbl.winner(row) if row >= 0 else None
            if cand is None:
                resp.null()
            else:
                ts, value = cand
                resp.array_start(2)
                resp.string(value)
                resp.u64(ts)
            return False
        if op == b"SET":
            key = need(args, 1)
            value = need(args, 2)
            ts = parse_u64(need(args, 3))
            row = self._tbl.upsert(key)
            self._tbl.write(row, ts, value)
            # local delta coalesces by the same LWW rule (exact, host-side)
            self._tbl.note_delta(row, ts, value)
            if self._tbl.pend_count() >= PENDING_DRAIN_THRESHOLD:
                self.drain()
            resp.ok()
            return True
        raise ParseError()

    def converge(self, key: bytes, delta: tuple) -> None:
        # buffer only: the serving path drains via drain_overdue in a
        # worker thread; sync callers (snapshot restore) drain explicitly
        value, ts = delta
        self._tbl.write(self._tbl.upsert(key), ts, value)

    def deltas_size(self) -> int:
        return self._tbl.deltas_size()

    def may_drain(self, args: list[bytes]) -> bool:
        """GET never drains (host winner compare); a SET may trigger the
        threshold drain, which the server offloads to a thread. +1: the
        SET about to run adds a row, so the threshold it will see inside
        apply is one higher than what is pending now."""
        return (
            bool(args)
            and args[0] == b"SET"
            and self._tbl.pend_count() + 1 >= PENDING_DRAIN_THRESHOLD
        )

    def drain_overdue(self) -> bool:
        """Cluster converge path: after buffering a batch, the manager
        offloads the drain to a worker thread when this trips."""
        return self._tbl.pend_count() >= PENDING_DRAIN_THRESHOLD

    def flush_deltas(self):
        return self._tbl.flush_deltas()

    # -- sync digest (cluster/syncdigest.py) ---------------------------------

    def sync_dirty_keys(self) -> list[bytes]:
        return [self._tbl.key_of(r) for r in self._tbl.export_sync_dirty()]

    def sync_canon(self, key: bytes) -> bytes | None:
        """Canonical per-key state: the LWW winner — an O(1) host read
        (every converged replica agrees on it by the exact
        (ts, value) rule)."""
        row = self._tbl.find(key)
        w = self._tbl.winner(row) if row >= 0 else None
        return None if w is None else repr(w).encode()

    # -- snapshot (persist.py): full state in the wire-delta shape ----------

    def dump_state(self):
        # the host winner IS the join the device converges to, so the
        # dump needs no device read; the drain keeps the device mirror
        # caught up for the sharded/mesh serving path
        self.drain()
        return self._tbl.dump()

    def load_state(self, batch) -> None:
        for key, delta in batch:
            self.converge(key, delta)

    # -- device drain -------------------------------------------------------

    @timed_drain("TREG", lambda self: self._tbl.pend_count())
    def drain(self) -> None:
        pend = self._tbl.export_pend()  # [(row, ts, value)], not yet cleared
        if not pend:
            return
        cap = self._round_cap(bucket(max(self._tbl.rows(), 1), self._key_cap))
        if cap != self._key_cap:
            self._key_cap = cap
            self._state = self._place(treg.grow(self._state, cap))
        self._maybe_compact_interner()
        if self._mesh is not None:
            self._drain_sharded(pend)
            self._tbl.fold_pend()
            return
        rows = [row for row, _ts, _v in pend]
        dense = len(rows) * DENSE_FRACTION >= self._key_cap
        b = self._key_cap if dense else bucket(len(rows))
        ki = pad_rows(b)
        d_ts = np.zeros(b, np.uint64)
        d_rank = np.zeros(b, np.uint64)
        d_vid = np.full(b, -1, np.int32)
        values: dict[int, bytes] = {}  # batch slot -> full delta string
        for i, (row, ts, value) in enumerate(pend):
            slot = row if dense else i
            ki[i] = row
            d_ts[slot] = ts
            d_rank[slot] = prefix_rank(value)
            d_vid[slot] = self._interner.intern(value)
            values[slot] = value
        ts_hi, ts_lo = planes.split64_np(d_ts)
        rank_hi, rank_lo = planes.split64_np(d_rank)
        if dense:
            self._state, tie, out_ts_hi, out_ts_lo, out_vid = _drain_dense(
                self._state, ts_hi, ts_lo, rank_hi, rank_lo, d_vid
            )
            slots = rows  # outputs are in dense key order
        else:
            self._state, tie, out_ts_hi, out_ts_lo, out_vid = _drain(
                self._state, ki, ts_hi, ts_lo, rank_hi, rank_lo, d_vid
            )
            slots = list(range(len(rows)))
        tie = np.asarray(tie)
        out_ts = planes.combine64_np(np.asarray(out_ts_hi), np.asarray(out_ts_lo))
        out_vid = np.asarray(out_vid).copy()
        if tie[slots].any():
            # prefix collision: full-string compare decides; patch losers
            patch_ki, patch_vid = [], []
            for row, slot in zip(rows, slots):
                if not tie[slot]:
                    continue
                cur_val = self._interner.lookup(int(out_vid[slot]))
                if values[slot] > cur_val:
                    patch_ki.append(row)
                    patch_vid.append(int(d_vid[slot]))
                    out_vid[slot] = d_vid[slot]
            if patch_ki:
                pb = bucket(len(patch_ki))
                pk = pad_rows(pb)  # distinct out-of-range pads drop
                pv = np.full(pb, -1, np.int32)
                pk[: len(patch_ki)] = patch_ki
                pv[: len(patch_vid)] = patch_vid
                self._state = _patch_vids(self._state, pk, pv)
        for row, slot in zip(rows, slots):
            self._cache[row] = (int(out_ts[slot]), int(out_vid[slot]))
        self._tbl.fold_pend()

    def _maybe_compact_interner(self) -> None:
        """Epoch compaction (weak-spot fix, VERDICT round 2): every value
        ever SET kept its interner slot forever. The host cache mirrors
        the device vid plane exactly (drain writes both), so when the
        table outgrows the live registers, rebuild it from the cache and
        REPLACE the device vid plane with the host-built remapped mirror
        — one transfer, no kernel. Runs under the repo lock at drain
        time, before any new pending values intern."""
        if len(self._interner) <= 2 * len(self._cache) + COMPACT_SLACK:
            return
        remap = self._interner.compact(
            vid for _ts, vid in self._cache.values() if vid >= 0
        )
        self._cache = {
            row: (ts, int(remap[vid]) if vid >= 0 else -1)
            for row, (ts, vid) in self._cache.items()
        }
        vids_by_row = np.full(self._key_cap, -1, np.int32)
        for row, (_ts, vid) in self._cache.items():
            vids_by_row[row] = vid
        new_vid = (
            shard_vec(self._mesh, vids_by_row)
            if self._mesh is not None
            else jax.numpy.asarray(vids_by_row)
        )
        self._state = self._state._replace(vid=new_vid)

    def _drain_sharded(self, pend) -> None:
        """Mesh-mode drain: payload columns [ts, rank, vid] route to the
        key blocks; ties come back per slot and resolve on host exactly
        like the single-chip path, patched with a routed vid scatter."""
        rows = [row for row, _ts, _v in pend]
        payload = np.zeros((len(rows), 3), np.uint64)
        values: dict[int, bytes] = {}
        for i, (row, ts, value) in enumerate(pend):
            payload[i, 0] = ts
            payload[i, 1] = prefix_rank(value)
            payload[i, 2] = self._interner.intern(value)  # vids are >= 0
            values[row] = value
        rps = self._key_cap // self._n_shards
        lr, d_hi, d_lo, slots = route_drain(
            np.asarray(rows, np.int64), payload, self._n_shards, rps
        )
        out = drain_sharded_treg(self._mesh, *self._state, lr, d_hi, d_lo)
        self._state = treg.TRegState(*out[:5])
        tie = np.asarray(out[5])
        out_ts = planes.combine64_np(np.asarray(out[6]), np.asarray(out[7]))
        out_vid = np.asarray(out[8]).copy()
        patch_rows: list[int] = []
        patch_vids: list[int] = []
        for j, g in enumerate(slots):
            if g < 0:
                continue
            row = int(g)
            if tie[j]:
                cur_val = self._interner.lookup(int(out_vid[j]))
                if values[row] > cur_val:
                    my_vid = self._interner.intern(values[row])
                    patch_rows.append(row)
                    patch_vids.append(my_vid)
                    out_vid[j] = my_vid
            self._cache[row] = (int(out_ts[j]), int(out_vid[j]))
        if patch_rows:
            pp = np.asarray(patch_vids, np.uint64).reshape(-1, 1)
            lr2, _p_hi, p_lo, _slots = route_drain(
                np.asarray(patch_rows, np.int64), pp, self._n_shards, rps
            )
            vid_new = patch_sharded_treg(
                self._mesh, self._state.vid, lr2, p_lo[:, 0].astype(np.int32)
            )
            self._state = self._state._replace(vid=vid_new)
