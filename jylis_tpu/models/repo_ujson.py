"""UJSON repo: host-resident causal-document keyspace.

Reference analog: repo_ujson.pony:14-110. Variadic argument shape: the
first arg is the database key, the LAST arg is the value/document (for
SET/INS/RM), and everything between is a path of nested-map keys
(repo_ujson.pony:45-49). GET/CLR take key + optional path only.

State lives on host (ops/ujson_host.py explains why this lattice is not
tensorised in round 1); the repo surface, delta flow, and reply shapes are
identical to the device-backed types.

Delta wire shape: the UJSON object itself (entries + causal context).
"""

from __future__ import annotations

from ..ops.ujson_host import UJSON
from .base import ParseError, need
from .help import RepoHelp

UJSON_HELP = RepoHelp(
    "UJSON",
    {
        "GET": "key [key...]",
        "SET": "key [key...] ujson",
        "CLR": "key [key...]",
        "INS": "key [key...] value",
        "RM": "key [key...] value",
    },
)


def _decode_path(parts: list[bytes]) -> tuple[str, ...]:
    return tuple(p.decode("utf-8", "replace") for p in parts)


class RepoUJSON:
    name = "UJSON"
    help = UJSON_HELP

    def __init__(self, identity: int):
        self._identity = identity
        self._data: dict[bytes, UJSON] = {}
        self._deltas: dict[bytes, UJSON] = {}

    def _data_for(self, key: bytes) -> UJSON:
        d = self._data.get(key)
        if d is None:
            d = self._data[key] = UJSON()
        return d

    def _delta_for(self, key: bytes) -> UJSON:
        d = self._deltas.get(key)
        if d is None:
            d = self._deltas[key] = UJSON()
        return d

    def _path_and_value(self, args: list[bytes]):
        """key [path...] value — at least key and value (repo_ujson.pony:45-49)."""
        if len(args) < 3:
            raise ParseError()
        return args[1], _decode_path(args[2:-1]), args[-1].decode("utf-8", "replace")

    def apply(self, resp, args: list[bytes]) -> bool:
        op = need(args, 0)
        if op == b"GET":
            key = need(args, 1)
            path = _decode_path(args[2:])
            doc = self._data.get(key)
            resp.string(doc.render(path) if doc is not None else "")
            return False
        if op == b"SET":
            key, path, value = self._path_and_value(args)
            try:
                self._data_for(key).set_doc(
                    self._identity, path, value, self._delta_for(key)
                )
            except ValueError:
                raise ParseError() from None
            resp.ok()
            return True
        if op == b"CLR":
            key = need(args, 1)
            path = _decode_path(args[2:])
            doc = self._data.get(key)
            if doc is not None:
                doc.clr(self._identity, path, self._delta_for(key))
            resp.ok()
            return True
        if op == b"INS":
            key, path, value = self._path_and_value(args)
            try:
                self._data_for(key).ins(
                    self._identity, path, value, self._delta_for(key)
                )
            except ValueError:
                raise ParseError() from None
            resp.ok()
            return True
        if op == b"RM":
            key, path, value = self._path_and_value(args)
            doc = self._data.get(key)
            try:
                if doc is not None:
                    doc.rm(self._identity, path, value, self._delta_for(key))
                else:
                    # still validates the value like the reference (:107)
                    from ..ops.ujson_host import parse_value

                    parse_value(value)
            except ValueError:
                raise ParseError() from None
            resp.ok()
            return True
        raise ParseError()

    def converge(self, key: bytes, delta: UJSON) -> None:
        self._data_for(key).converge(delta)

    # -- snapshot (persist.py): full state in the wire-delta shape ----------

    def dump_state(self):
        # keep docs whose causal context is non-trivial even when empty of
        # entries: the tombstone knowledge is what makes removals stick
        return [
            (key, doc)
            for key, doc in sorted(self._data.items())
            if doc.entries or doc.ctx.vv or doc.ctx.cloud
        ]

    def load_state(self, batch) -> None:
        for key, delta in batch:
            self.converge(key, delta)

    def deltas_size(self) -> int:
        return len(self._deltas)

    def flush_deltas(self):
        out = sorted(self._deltas.items())
        self._deltas.clear()
        return out

    def drain(self) -> None:  # host-resident: nothing buffered
        pass
