"""UJSON repo: causal-document keyspace with device-RESIDENT hot keys.

Reference analog: repo_ujson.pony:14-110. Variadic argument shape: the
first arg is the database key, the LAST arg is the value/document (for
SET/INS/RM), and everything between is a path of nested-map keys
(repo_ujson.pony:45-49). GET/CLR take key + optional path only.

Every key is in exactly ONE of two modes:

* host mode (``_data``): the authoritative doc is a host ``UJSON``
  (ops/ujson_host.py). Keys are born here; local writes always happen
  here. This is the reference's shape.
* device mode (``_res``): the doc lives as a packed row in the
  device-resident store (ops/ujson_resident.ResidentStore). A key is
  promoted the first time its anti-entropy fan-in earns device work, and
  from then on drains encode ONLY the new deltas and fold them into the
  resident row on device — the full document is never re-encoded or
  host-walked again (the round-3 bottleneck, and the reference's
  per-delta full-doc converge loop, repo_ujson.pony:96-110).

Reads on device-mode keys decode lazily and cache; the cache invalidates
per key when a fold touches the key. Local writes demote the key back to
host mode first (observed-remove mutators need the current doc anyway),
so write-hot keys simply stay in the reference's host shape while
anti-entropy-hot keys stay resident.

Seqs past u32 exceed every device layout; those keys fall back to host
mode permanently (same contract as round 3).

Delta wire shape: the UJSON object itself (entries + causal context).
"""

from __future__ import annotations

from ..ops.ujson_host import UJSON
from .base import ParseError, need
from .help import RepoHelp

# pending deltas per key at which a SINGLE non-resident key's drain moves
# to the device (and the key becomes resident): below this the host loop
# wins against an unshared dispatch round-trip
DEVICE_FANIN_MIN = 256
# per-key fan-in worth joining a SEGMENTED drain: when many keys drain
# together the dispatch is shared, so smaller fan-ins than
# DEVICE_FANIN_MIN pay for their slice of the launch. Measured crossover
# vs the host loop on single-entry deltas: ~64-128 per key (bench.py
# --config ujson-multikey; the host fold is O(D^2) per key, the delta
# encode is O(D))
SEG_FANIN_MIN = 64
# buffered remote deltas across all keys before the converge path forces
# a drain: bounds host memory for write-hot, never-read keys the same way
# TLOG's PENDING_DRAIN_THRESHOLD does (repo_tlog.py:41)
PENDING_TOTAL_MAX = 4096
# a GET-path drain on a RESIDENT key with fewer pending deltas than this
# serves them host-side into the read cache instead of dispatching a
# device fold: the lattice join is idempotent, so the deltas stay pending
# and fold for real at the next full drain — a read-heavy key with a
# delta trickle never pays a device round trip per GET
TRICKLE_MAX = 16

UJSON_HELP = RepoHelp(
    "UJSON",
    {
        "GET": "key [key...]",
        "SET": "key [key...] ujson",
        "CLR": "key [key...]",
        "INS": "key [key...] value",
        "RM": "key [key...] value",
    },
)


def _decode_path(parts: list[bytes]) -> tuple[str, ...]:
    return tuple(p.decode("utf-8", "replace") for p in parts)


class RepoUJSON:
    name = "UJSON"
    help = UJSON_HELP

    def __init__(self, identity: int, mesh="auto", engine=None):
        from ..parallel import serving_mesh

        self._identity = identity
        # native serving engine (native/serve_engine.cpp): validated
        # INS/SET/RM/CLR commands bank in its write queue (_flush_queue
        # applies them, in arrival order, before any other UJSON work
        # reads or writes), and GET replies this repo rendered are
        # memoised per (key, path) so repeat reads settle natively —
        # every write here invalidates the overlapping memos
        self.engine = engine
        # mesh mode: the resident store's row axis shards over the
        # serving mesh and drains use the row-aligned fold — SPMD with
        # zero collectives, like every plane-backed type
        self._mesh = serving_mesh() if mesh == "auto" else mesh
        self._data: dict[bytes, UJSON] = {}
        self._deltas: dict[bytes, UJSON] = {}
        self._pend: dict[bytes, list[UJSON]] = {}  # buffered remote deltas
        self._pend_total = 0  # deltas across keys, O(1) overdue check
        self._overdue = False  # some key's fan-in reached DEVICE_FANIN_MIN
        self._res = None  # ResidentStore, created on first promotion
        self._res_cache: dict[bytes, UJSON] = {}  # decoded device-mode docs
        # pending deltas already host-converged into the cached view
        # (the GET-path trickle), so repeat reads don't re-walk the doc
        self._res_applied: dict[bytes, int] = {}
        self._host_only: set[bytes] = set()  # seqs past u32: never promote
        self._sync_dirty: set[bytes] = set()  # since last digest pass

    # -- mode plumbing -------------------------------------------------------

    def _store(self):
        if self._res is None:
            from ..ops.ujson_resident import ResidentStore

            shard_fn = None
            if self._mesh is not None:
                from ..parallel import shard_docbatch

                mesh = self._mesh
                shard_fn = lambda b: shard_docbatch(mesh, b)  # noqa: E731
            self._res = ResidentStore(mesh=self._mesh, shard_fn=shard_fn)
        return self._res

    def _is_resident(self, key: bytes) -> bool:
        return self._res is not None and key in self._res

    def _view(self, key: bytes) -> UJSON | None:
        """The current doc for reading: host doc, or the resident row
        decoded through the per-key cache."""
        doc = self._data.get(key)
        if doc is not None:
            return doc
        if self._is_resident(key):
            doc = self._res_cache.get(key)
            if doc is None:
                doc = self._res.read(key)
                self._res_cache[key] = doc
            return doc
        return None

    def _demote(self, key: bytes) -> None:
        """Move a device-mode key back to host mode (before any local
        write: observed-remove mutators walk the doc, and host mode is
        where local delta accumulation lives)."""
        if not self._is_resident(key):
            return
        doc = self._res_cache.pop(key, None)
        self._res_applied.pop(key, None)
        if doc is not None:
            self._res.discard(key)
        else:
            doc = self._res.evict(key)
        self._data[key] = doc

    def _data_for(self, key: bytes) -> UJSON:
        d = self._data.get(key)
        if d is None:
            d = self._data[key] = UJSON()
        return d

    def _delta_for(self, key: bytes) -> UJSON:
        d = self._deltas.get(key)
        if d is None:
            d = self._deltas[key] = UJSON()
        return d

    def _path_and_value(self, args: list[bytes]):
        """key [path...] value — at least key and value (repo_ujson.pony:45-49)."""
        if len(args) < 3:
            raise ParseError()
        return args[1], _decode_path(args[2:-1]), args[-1].decode("utf-8", "replace")

    def _flush_queue(self) -> None:
        """Apply every write the native engine banked (in arrival order):
        INS, SET, RM and CLR, exactly the sequences their apply() branches
        run — observed-remove ops observe (drain) first. Runs before any
        other UJSON work so the queue is invisible to reads, flushes,
        drains and snapshots; the engine pre-validated each value token
        (engine.h ujson_prim_ok / ujson_doc_ok), so the applies cannot
        fail (the +OK replies are already on the wire)."""
        if self.engine is None or not self.engine.uq_count():
            return
        for args in self.engine.uq_drain():
            op = args[0]
            if op == b"CLR":
                key = args[1]
                self._drain_key(key)  # observed-remove: observe first
                self._demote(key)
                doc = self._data.get(key)
                if doc is not None:
                    doc.clr(
                        self._identity, _decode_path(args[2:]),
                        self._delta_for(key),
                    )
                self._sync_dirty.add(key)
                continue
            key, path, value = self._path_and_value(args)
            if op == b"SET":
                self._drain_key(key)  # SET clears OBSERVED dots
                self._demote(key)
                self._data_for(key).set_doc(
                    self._identity, path, value, self._delta_for(key)
                )
            elif op == b"RM":
                self._drain_key(key)  # observed-remove: observe first
                self._demote(key)
                doc = self._data.get(key)
                if doc is not None:
                    doc.rm(self._identity, path, value, self._delta_for(key))
            else:  # INS
                self._demote(key)
                self._data_for(key).ins(
                    self._identity, path, value, self._delta_for(key)
                )
            self._sync_dirty.add(key)

    def prepare_flush(self) -> None:
        """Manager hook (flush_async): drain the write queue in a worker
        thread before the loop-side delta flush — a queued write on a
        resident key demotes, which can decode (a blocking device pull)."""
        self._flush_queue()

    def apply(self, resp, args: list[bytes]) -> bool:
        self._flush_queue()
        op = need(args, 0)
        if op in (b"SET", b"CLR", b"INS", b"RM") and len(args) >= 2:
            self._sync_dirty.add(args[1])
            if self.engine is not None:
                # a write applied on THIS path (deferred by the engine, or
                # a direct apply) must drop the overlapping render memos,
                # exactly as a natively banked one does at bank time
                self.engine.uj_invalidate(
                    args[1],
                    args[2:] if op == b"CLR" else args[2:-1],
                    subtree=op in (b"SET", b"CLR"),
                )
        if op == b"GET":
            key = need(args, 1)
            self._drain_key(key)
            path = _decode_path(args[2:])
            doc = self._view(key)
            text = doc.render(path) if doc is not None else ""
            resp.string(text)
            if self.engine is not None and doc is not None:
                body = text.encode()
                # memo repair (the TLOG base-repair shape): the next GET
                # of this (key, path) settles natively on these bytes.
                # Keys with no document never memoise — a read-only scan
                # over absent keys must not grow engine rows without
                # bound (rows are bounded by the written keyspace)
                self.engine.uj_memo_put(
                    key, args[2:], b"$%d\r\n%s\r\n" % (len(body), body)
                )
            return False
        if op == b"SET":
            key, path, value = self._path_and_value(args)
            self._drain_key(key)  # SET clears OBSERVED dots: observe first
            self._demote(key)
            try:
                self._data_for(key).set_doc(
                    self._identity, path, value, self._delta_for(key)
                )
            except ValueError:
                raise ParseError() from None
            resp.ok()
            return True
        if op == b"CLR":
            key = need(args, 1)
            self._drain_key(key)  # observed-remove: observe first
            self._demote(key)
            path = _decode_path(args[2:])
            doc = self._data.get(key)
            if doc is not None:
                doc.clr(self._identity, path, self._delta_for(key))
            resp.ok()
            return True
        if op == b"INS":
            key, path, value = self._path_and_value(args)
            self._demote(key)
            try:
                self._data_for(key).ins(
                    self._identity, path, value, self._delta_for(key)
                )
            except ValueError:
                raise ParseError() from None
            resp.ok()
            return True
        if op == b"RM":
            key, path, value = self._path_and_value(args)
            self._drain_key(key)  # observed-remove: observe first
            self._demote(key)
            doc = self._data.get(key)
            try:
                if doc is not None:
                    doc.rm(self._identity, path, value, self._delta_for(key))
                else:
                    # still validates the value like the reference (:107)
                    from ..ops.ujson_host import parse_value

                    parse_value(value)
            except ValueError:
                raise ParseError() from None
            resp.ok()
            return True
        raise ParseError()

    def converge(self, key: bytes, delta: UJSON) -> None:
        lst = self._pend.setdefault(key, [])
        lst.append(delta)
        self._pend_total += 1
        self._sync_dirty.add(key)
        if self.engine is not None:
            # a remote delta can change any subtree: drop every render
            # memo for the key (path () with subtree=True covers all)
            self.engine.uj_invalidate(key, (), subtree=True)
        if len(lst) >= DEVICE_FANIN_MIN:
            self._overdue = True

    def drain_overdue(self) -> bool:
        """Cluster converge path: the manager offloads a full drain to a
        worker thread when a key's fan-in reaches device size or the
        total buffered deltas hit the cap — a write-hot, never-read key
        stays bounded like every other type."""
        return self._overdue or self._pend_total >= PENDING_TOTAL_MAX

    # INS included: it never drains, but on a resident key it demotes —
    # which can decode (a blocking device pull) and must not run on the
    # event loop
    may_drain_OPS = (b"GET", b"SET", b"CLR", b"RM", b"INS")

    # banked native-queue commands above which even a host-only flush
    # offloads to a thread (a bounded event-loop stall beats none)
    UQ_INLINE_MAX = 1024

    def may_drain(self, args: list[bytes]) -> bool:
        """Commands that will touch the device get offloaded to a thread
        (manager.apply_async): a device-sized pending fan-in, a resident
        key whose pending exceeds the trickle budget (the drain folds on
        device), or a resident read/demotion that must decode (cache
        miss). A trickle on a warm cache stays on the loop — the drain
        serves it host-side in microseconds. A non-empty native write
        queue offloads only when its flush can actually touch the device
        (a resident store exists, a fan-in reached device size, or the
        queue is large): a small host-only flush runs inline, so the one
        deferred command that flushes it never opens a lock window that
        routes every OTHER connection's burst off the native path
        (server/server.py read-loop busy check — the round-5 shape
        threaded every flush and turned each UJSON defer into a
        whole-node demotion storm under concurrency)."""
        if self.engine is not None and self.engine.uq_count():
            if (
                self._res is not None
                or self._overdue
                or self._pend_total >= PENDING_TOTAL_MAX
                or self.engine.uq_count() > self.UQ_INLINE_MAX
            ):
                return True
            # host-only flush: fall through to this command's own checks
        if len(args) < 2 or args[0] not in self.may_drain_OPS:
            return False
        key = args[1]
        if len(self._pend.get(key, ())) >= DEVICE_FANIN_MIN:
            return True
        if self._is_resident(key):
            return (
                len(self._pend.get(key, ())) > TRICKLE_MAX
                or key not in self._res_cache
            )
        return False

    def _drain_key(self, key: bytes) -> None:
        deltas = self._pend.get(key)
        if not deltas:
            return
        if self._is_resident(key):
            if len(deltas) <= TRICKLE_MAX:
                # read-path trickle: converge into the cached view on the
                # host (idempotent join — the deltas stay pending for the
                # next full drain's device fold); _res_applied tracks how
                # many this cache already absorbed, so repeat reads don't
                # re-walk the doc per pending delta
                doc = self._res_cache.get(key)
                if doc is None:
                    doc = self._res.read(key)
                    self._res_cache[key] = doc
                    self._res_applied.pop(key, None)
                for d in deltas[self._res_applied.get(key, 0):]:
                    doc.converge(d)
                self._res_applied[key] = len(deltas)
                return
            self._pend.pop(key)
            self._pend_total -= len(deltas)
            rest = self._resident_fold({key: deltas})
            if not rest:
                return
            deltas = rest[key]
        elif len(deltas) >= DEVICE_FANIN_MIN and key not in self._host_only:
            self._pend.pop(key)
            self._pend_total -= len(deltas)
            rest = self._resident_fold({key: deltas})
            if not rest:
                return
            deltas = rest[key]
        else:
            self._pend.pop(key)
            self._pend_total -= len(deltas)
        doc = self._data_for(key)
        for d in deltas:
            doc.converge(d)

    def _resident_fold(self, groups: dict[bytes, list[UJSON]]):
        """Promote keys as needed and fold their pending deltas into the
        resident rows — ONE device dispatch for every key in the drain.
        Returns the groups that must fall back to the host loop (seqs
        beyond the u64/32 device layouts)."""
        store = self._store()
        fallback: dict[bytes, list[UJSON]] = {}

        to_admit = [k for k in groups if k not in store]
        if to_admit and store.full():
            # HBM admission gate (ResidentStore.BYTE_BUDGET): further
            # keys serve from the host lattice; resident keys keep their
            # rows
            for k in to_admit:
                fallback[k] = groups[k]
            to_admit = []
        if to_admit:
            items = [(k, self._data.get(k) or UJSON()) for k in to_admit]
            try:
                store.admit(items)
            except OverflowError:
                # isolate the un-encodable docs; the rest still promote
                items, bulk = [], items
                for k, d in bulk:
                    try:
                        store.admit([(k, d)])
                    except OverflowError:
                        self._host_only.add(k)
                        fallback[k] = groups[k]
                        continue
                    items.append((k, d))
            for k, d in items:
                self._data.pop(k, None)
                self._res_cache[k] = d  # row state == this doc, cache it

        fold = {k: v for k, v in groups.items() if k not in fallback}
        try:
            store.fold_in(fold)
        except OverflowError:
            for k, v in fold.items():
                try:
                    store.fold_in({k: v})
                except OverflowError:
                    self._demote(k)
                    self._host_only.add(k)
                    fallback[k] = v
                else:
                    self._res_cache.pop(k, None)
                    self._res_applied.pop(k, None)
        else:
            for k in fold:
                self._res_cache.pop(k, None)
                self._res_applied.pop(k, None)
        return fallback

    # -- sync digest (cluster/syncdigest.py) ---------------------------------

    def sync_prepare(self) -> None:
        """Fold all pending deltas in ONE device/host pass before the
        canon reads (a per-key fold would dispatch per dirty key)."""
        self._flush_queue()
        self.drain()

    def sync_dirty_keys(self) -> list[bytes]:
        out = list(self._sync_dirty)
        self._sync_dirty.clear()
        return out

    def sync_canon(self, key: bytes) -> bytes | None:
        """Canonical per-key state: the doc's dot-store + causal context
        with every unordered container sorted, so converged replicas
        (whose dict/set iteration orders differ) hash identically."""
        doc = self._view(key)
        if doc is None or not (doc.entries or doc.ctx.vv or doc.ctx.cloud):
            return None
        ents = sorted(
            (dot, path, token) for dot, (path, token) in doc.entries.items()
        )
        return repr(
            (ents, sorted(doc.ctx.vv.items()), sorted(doc.ctx.cloud))
        ).encode()

    # -- snapshot (persist.py): full state in the wire-delta shape ----------

    def dump_state(self):
        self._flush_queue()
        self.drain()
        docs = dict(self._data)
        if self._res is not None:
            docs.update(self._res.dump())
        # keep docs whose causal context is non-trivial even when empty of
        # entries: the tombstone knowledge is what makes removals stick
        return [
            (key, doc)
            for key, doc in sorted(docs.items())
            if doc.entries or doc.ctx.vv or doc.ctx.cloud
        ]

    def load_state(self, batch) -> None:
        for key, delta in batch:
            self.converge(key, delta)

    def deltas_size(self) -> int:
        # the banked queue is NOT drained here: this runs on the event
        # loop (proactive flush), and a queued write on a resident key
        # demotes with a blocking device decode. prepare_flush (threaded,
        # manager.flush_async / clean_shutdown) drains it; deltas from
        # still-banked writes simply ship on the next heartbeat flush.
        return len(self._deltas)

    def flush_deltas(self):
        out = sorted(self._deltas.items())
        self._deltas.clear()
        return out

    def drain(self) -> None:
        self._flush_queue()
        # device pass first: every resident key with pending, plus every
        # key whose fan-in earns a slice of a shared launch, folds in ONE
        # dispatch; what remains (small fan-ins on host-mode keys, or
        # everything on layout overflow) host-loops
        groups = {
            k: lst
            for k, lst in self._pend.items()
            if k not in self._host_only
            and (self._is_resident(k) or len(lst) >= SEG_FANIN_MIN)
        }
        # SEG_FANIN_MIN only pays when the dispatch is SHARED: a lone
        # non-resident key below the single-dispatch crossover stays on
        # the host loop
        if len(groups) == 1:
            k = next(iter(groups))
            if not self._is_resident(k) and len(groups[k]) < DEVICE_FANIN_MIN:
                groups = {}
        if groups:
            for k in groups:
                self._pend.pop(k)
            self._pend_total -= sum(len(v) for v in groups.values())
            fallback = self._resident_fold(groups)
            for k, lst in fallback.items():
                doc = self._data_for(k)
                for d in lst:
                    doc.converge(d)
        for key in list(self._pend):
            self._drain_key(key)
        self._overdue = False
