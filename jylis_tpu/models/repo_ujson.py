"""UJSON repo: causal-document keyspace, host-served with a device fan-in.

Reference analog: repo_ujson.pony:14-110. Variadic argument shape: the
first arg is the database key, the LAST arg is the value/document (for
SET/INS/RM), and everything between is a path of nested-map keys
(repo_ujson.pony:45-49). GET/CLR take key + optional path only.

Authoritative state lives on host (ops/ujson_host.py explains why);
incoming anti-entropy deltas buffer per key — bounded by drain_overdue
thresholds like every device-backed repo — and converge at drain time.
A full drain folds EVERY key whose fan-in earns device work in ONE
segmented dispatch (ops/ujson_device.fold_segments, the (K, D, W)
log-depth associative fold; keys-sharded over the serving mesh when one
is active), then host-converges one folded delta per key. Small fan-ins
stay on the host loop, which beats a device round-trip at small sizes
(measured crossover: bench.py --config ujson-multikey).

Delta wire shape: the UJSON object itself (entries + causal context).
"""

from __future__ import annotations

from ..ops.ujson_host import UJSON
from .base import ParseError, need
from .help import RepoHelp

# pending deltas per key at which a SINGLE key's fold moves to the
# device: below this the host loop wins against an unshared dispatch
# round-trip
DEVICE_FANIN_MIN = 256
# per-key fan-in worth joining a SEGMENTED drain: when many keys drain
# together the dispatch is shared, so smaller fan-ins than
# DEVICE_FANIN_MIN pay for their slice of the launch (one (K, D, W)
# fold_segments call for all of them). Measured crossover vs the host
# loop on single-entry deltas: ~64-128 per key (bench.py --config
# ujson-multikey; the host fold is O(D^2) per key, encode is O(D))
SEG_FANIN_MIN = 64
# buffered remote deltas across all keys before the converge path forces
# a drain: bounds host memory for write-hot, never-read keys the same way
# TLOG's PENDING_DRAIN_THRESHOLD does (repo_tlog.py:41)
PENDING_TOTAL_MAX = 4096

UJSON_HELP = RepoHelp(
    "UJSON",
    {
        "GET": "key [key...]",
        "SET": "key [key...] ujson",
        "CLR": "key [key...]",
        "INS": "key [key...] value",
        "RM": "key [key...] value",
    },
)


def _decode_path(parts: list[bytes]) -> tuple[str, ...]:
    return tuple(p.decode("utf-8", "replace") for p in parts)


class RepoUJSON:
    name = "UJSON"
    help = UJSON_HELP

    def __init__(self, identity: int, mesh="auto"):
        from ..parallel import serving_mesh

        self._identity = identity
        # mesh mode: the segmented drain's key axis shards over the
        # serving mesh (parallel.shard_docbatch) — the fold runs SPMD
        # with zero collectives, like every plane-backed type
        self._mesh = serving_mesh() if mesh == "auto" else mesh
        self._data: dict[bytes, UJSON] = {}
        self._deltas: dict[bytes, UJSON] = {}
        self._pend: dict[bytes, list[UJSON]] = {}  # buffered remote deltas
        self._pend_total = 0  # deltas across keys, O(1) overdue check
        self._shift_hint: int | None = None  # 32 once a drain went wide
        self._overdue = False  # some key's fan-in reached DEVICE_FANIN_MIN

    def _data_for(self, key: bytes) -> UJSON:
        d = self._data.get(key)
        if d is None:
            d = self._data[key] = UJSON()
        return d

    def _delta_for(self, key: bytes) -> UJSON:
        d = self._deltas.get(key)
        if d is None:
            d = self._deltas[key] = UJSON()
        return d

    def _path_and_value(self, args: list[bytes]):
        """key [path...] value — at least key and value (repo_ujson.pony:45-49)."""
        if len(args) < 3:
            raise ParseError()
        return args[1], _decode_path(args[2:-1]), args[-1].decode("utf-8", "replace")

    def apply(self, resp, args: list[bytes]) -> bool:
        op = need(args, 0)
        if op == b"GET":
            key = need(args, 1)
            self._drain_key(key)
            path = _decode_path(args[2:])
            doc = self._data.get(key)
            resp.string(doc.render(path) if doc is not None else "")
            return False
        if op == b"SET":
            key, path, value = self._path_and_value(args)
            self._drain_key(key)  # SET clears OBSERVED dots: observe first
            try:
                self._data_for(key).set_doc(
                    self._identity, path, value, self._delta_for(key)
                )
            except ValueError:
                raise ParseError() from None
            resp.ok()
            return True
        if op == b"CLR":
            key = need(args, 1)
            self._drain_key(key)  # observed-remove: observe first
            path = _decode_path(args[2:])
            doc = self._data.get(key)
            if doc is not None:
                doc.clr(self._identity, path, self._delta_for(key))
            resp.ok()
            return True
        if op == b"INS":
            key, path, value = self._path_and_value(args)
            try:
                self._data_for(key).ins(
                    self._identity, path, value, self._delta_for(key)
                )
            except ValueError:
                raise ParseError() from None
            resp.ok()
            return True
        if op == b"RM":
            key, path, value = self._path_and_value(args)
            self._drain_key(key)  # observed-remove: observe first
            doc = self._data.get(key)
            try:
                if doc is not None:
                    doc.rm(self._identity, path, value, self._delta_for(key))
                else:
                    # still validates the value like the reference (:107)
                    from ..ops.ujson_host import parse_value

                    parse_value(value)
            except ValueError:
                raise ParseError() from None
            resp.ok()
            return True
        raise ParseError()

    def converge(self, key: bytes, delta: UJSON) -> None:
        lst = self._pend.setdefault(key, [])
        lst.append(delta)
        self._pend_total += 1
        if len(lst) >= DEVICE_FANIN_MIN:
            self._overdue = True

    def drain_overdue(self) -> bool:
        """Cluster converge path: the manager offloads a full drain to a
        worker thread when a key's fan-in reaches device size or the
        total buffered deltas hit the cap — a write-hot, never-read key
        stays bounded like every other type."""
        return self._overdue or self._pend_total >= PENDING_TOTAL_MAX

    may_drain_OPS = (b"GET", b"SET", b"CLR", b"RM")

    def may_drain(self, args: list[bytes]) -> bool:
        """A command that observes a key with a device-sized pending
        fan-in dispatches; the server offloads it to a thread
        (manager.apply_async)."""
        return (
            len(args) >= 2
            and args[0] in self.may_drain_OPS
            and len(self._pend.get(args[1], ())) >= DEVICE_FANIN_MIN
        )

    def _drain_key(self, key: bytes) -> None:
        deltas = self._pend.pop(key, None)
        if not deltas:
            return
        self._pend_total -= len(deltas)
        doc = self._data_for(key)
        if len(deltas) >= DEVICE_FANIN_MIN:
            try:
                doc.converge(self._device_fold_keys([deltas])[0])
                return
            except OverflowError:
                # seqs beyond the device layouts (u32 planes): the host
                # lattice handles unbounded ints — fall through
                pass
        for d in deltas:
            doc.converge(d)

    def _device_fold_keys(self, groups: list[list[UJSON]]) -> list[UJSON]:
        """Fold K keys' fan-ins on the TPU in ONE dispatch (segmented
        fold, one layout spanning every group); in mesh mode the key
        axis is sharded across the serving mesh."""
        from ..ops import ujson_device as dev
        from ..parallel import shard_docbatch
        from ..utils.batching import bucket

        n_keys = len(groups)
        # bucket the key axis (and round to the mesh's keys axis): every
        # distinct K would otherwise be a fresh XLA compile of the fold
        target = bucket(max(n_keys, 1), 1)
        if self._mesh is not None:
            target += -target % self._mesh.devices.size
        groups = groups + [[] for _ in range(target - n_keys)]
        flat = [d for g in groups for d in g]
        rids: set[int] = set()
        for d in flat:
            rids.update(r for r, _ in d.entries)
            rids.update(d.ctx.vv)
            rids.update(r for r, _ in d.ctx.cloud)
        n_rep = bucket(max(len(rids), 1), 4)
        pays: dict[tuple, int] = {}
        rev: list[tuple] = []

        def pay_ids(path, token):
            k = (path, token)
            if k not in pays:
                pays[k] = len(rev)
                rev.append(k)
            return pays[k]

        rid_cols: dict[int, int] = {}
        batch, shift = dev.encode_doc_groups_auto(
            groups, rid_cols, pay_ids, n_rep, prefer=self._shift_hint
        )
        # hysteresis: once a drain needed the wide layout, skip the doomed
        # narrow attempt on subsequent drains (seqs only grow)
        self._shift_hint = 32 if shift == 32 else None
        if self._mesh is not None:
            batch = shard_docbatch(self._mesh, batch)
        folded = dev.fold_segments(batch, shift=shift)
        cols_rid = {c: r for r, c in rid_cols.items()}
        docs = dev.decode_batch(folded, cols_rid, rev.__getitem__, shift=shift)
        return docs[:n_keys]

    # -- snapshot (persist.py): full state in the wire-delta shape ----------

    def dump_state(self):
        self.drain()
        # keep docs whose causal context is non-trivial even when empty of
        # entries: the tombstone knowledge is what makes removals stick
        return [
            (key, doc)
            for key, doc in sorted(self._data.items())
            if doc.entries or doc.ctx.vv or doc.ctx.cloud
        ]

    def load_state(self, batch) -> None:
        for key, delta in batch:
            self.converge(key, delta)

    def deltas_size(self) -> int:
        return len(self._deltas)

    def flush_deltas(self):
        out = sorted(self._deltas.items())
        self._deltas.clear()
        return out

    def drain(self) -> None:
        # segmented device pass first: every key whose fan-in earns a
        # slice of a shared launch folds in ONE dispatch; what remains
        # (small fan-ins, or everything on layout overflow) host-loops
        big = [
            k for k, lst in self._pend.items() if len(lst) >= SEG_FANIN_MIN
        ]
        # SEG_FANIN_MIN only pays when the dispatch is SHARED: a lone key
        # below the single-dispatch crossover stays on the host loop
        if len(big) == 1 and len(self._pend[big[0]]) < DEVICE_FANIN_MIN:
            big = []
        if big:
            try:
                folded = self._device_fold_keys([self._pend[k] for k in big])
            except OverflowError:
                pass  # host lattice handles unbounded ints below
            else:
                for key, delta in zip(big, folded):
                    deltas = self._pend.pop(key)
                    self._pend_total -= len(deltas)
                    self._data_for(key).converge(delta)
        for key in list(self._pend):
            self._drain_key(key)
        self._overdue = False
