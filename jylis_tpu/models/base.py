"""Shared repo plumbing: argument parsing, batching helpers, capacity math.

The repo plugin contract (mirroring RepoAny, repo_manager.pony:5-10):

    apply(resp, args: list[bytes]) -> bool   # True if data changed;
                                             # raises ParseError for help
    deltas_size() -> int
    flush_deltas() -> list[(key: bytes, delta)]
    converge(key: bytes, delta) -> None

plus ``drain()`` (device-repo specific): apply all buffered mutations /
deltas to device state in one fused batch.
"""

from __future__ import annotations

U64_MAX = (1 << 64) - 1


class ParseError(Exception):
    """Command didn't parse; the manager replies with help text."""


def need(args: list[bytes], i: int) -> bytes:
    try:
        return args[i]
    except IndexError:
        raise ParseError() from None


def parse_u64(b: bytes) -> int:
    """Strict unsigned 64-bit parse (Pony String.u64() behavior: digits
    only, no sign, must fit)."""
    if not b.isdigit():
        raise ParseError()
    v = int(b)
    if v > U64_MAX:
        raise ParseError()
    return v


def parse_opt_count(args: list[bytes], i: int) -> int:
    """Optional count arg: any missing/unparseable value means "all"
    (the reference's try-usize-else -1 trick, repo_tlog.pony:49-50)."""
    try:
        return parse_u64(args[i])
    except (ParseError, IndexError):
        return U64_MAX


# batch-padding row index: out of range for any real keyspace, so padded
# scatter updates fall into mode="drop" instead of colliding with row 0
PAD_ROW = (1 << 31) - 1


def pad_rows(n: int):
    """(n,) int32 of DISTINCT out-of-range rows (PAD_ROW, PAD_ROW-1, ...).

    Kernels scatter with ``unique_indices=True``; repeating PAD_ROW itself
    for every padded slot would make that hint a lie (duplicate indices
    under the hint are documented UB, even ones mode="drop" discards).
    Distinct descending pads keep the whole index vector genuinely unique —
    real keyspaces are far smaller than PAD_ROW - n."""
    import numpy as np

    return (PAD_ROW - np.arange(n)).astype(np.int32)


def bucket(n: int, lo: int = 16) -> int:
    """Next power of two >= n (>= lo): pads batch dims so the jit cache
    stays small — every distinct shape is a fresh XLA compile."""
    b = lo
    while b < n:
        b <<= 1
    return b
