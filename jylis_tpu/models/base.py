"""Shared repo plumbing: argument parsing, batching helpers, capacity math.

The repo plugin contract (mirroring RepoAny, repo_manager.pony:5-10):

    apply(resp, args: list[bytes]) -> bool   # True if data changed;
                                             # raises ParseError for help
    deltas_size() -> int
    flush_deltas() -> list[(key: bytes, delta)]
    converge(key: bytes, delta) -> None

plus ``drain()`` (device-repo specific): apply all buffered mutations /
deltas to device state in one fused batch.
"""

from __future__ import annotations

U64_MAX = (1 << 64) - 1


class ParseError(Exception):
    """Command didn't parse; the manager replies with help text."""


def need(args: list[bytes], i: int) -> bytes:
    try:
        return args[i]
    except IndexError:
        raise ParseError() from None


def parse_u64(b: bytes) -> int:
    """Strict unsigned 64-bit parse (Pony String.u64() behavior: digits
    only, no sign, must fit)."""
    if not b.isdigit():
        raise ParseError()
    v = int(b)
    if v > U64_MAX:
        raise ParseError()
    return v


def parse_opt_count(args: list[bytes], i: int) -> int:
    """Optional count arg: any missing/unparseable value means "all"
    (the reference's try-usize-else -1 trick, repo_tlog.pony:49-50)."""
    try:
        return parse_u64(args[i])
    except (ParseError, IndexError):
        return U64_MAX


# batching helpers live in utils/batching.py (import-cycle-free ground
# shared with parallel/); re-exported here for the repos' convenience
from ..utils.batching import PAD_ROW, bucket, pad_rows  # noqa: E402,F401
