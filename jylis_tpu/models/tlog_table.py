"""Host-state backends for the TLOG repo.

TLOG's host bookkeeping — key interning, the per-row pending window,
length/cutoff caches, the merged-view memo that serves SIZE/GET without
device reads, and the outbound delta accumulators — lives behind one
table interface with two implementations (the counter_table.py pattern):

* `PyTlogTable` — pure-Python, the semantic oracle and the fallback when
  no C++ toolchain is available.
* `NativeTlogTable` — a view over the native serving engine's TLOG table
  (native/engine.h TlogTable). The same state the server's batch applier
  mutates, so INS/SIZE/GET/CUTOFF settled natively and Python-side
  drains/flushes share one source of truth.

Semantics mirror repo_tlog.pony:16-111 via docs tlog.md: entries dedup on
(ts, value), cutoffs are grow-only and filter the view, TRIM/CLR raise
cutoffs. The merged view (drained ∪ pending, deduped, cutoff-filtered) is
memoised per row with the exact state-key discipline the round-4 repo
used; additionally the drained "base" CARRIES ACROSS drains — when the
memo is current at drain time, the post-drain row content equals the memo
filtered by the returned cutoff (the device performs the same lattice
join), so reads keep serving host-side without a device gather. A length
mismatch at that handoff invalidates the base (``size`` then returns -1
and the repo rebuilds it from one device row gather via ``set_base``).
"""

from __future__ import annotations

# drain thresholds; native/engine.h TlogTable must match
ROW_DRAIN_THRESHOLD = 1024
PENDING_DRAIN_THRESHOLD = 4096


class _Row:
    __slots__ = (
        "pend", "pend_cutoff", "len_cache", "cut_cache", "base", "base_valid",
        "memo", "memo_valid", "memo_plen", "memo_cut", "gen",
        "delta", "delta_cutoff", "delta_present", "touched",
    )

    def __init__(self):
        self.pend: list[tuple[int, bytes]] = []
        self.pend_cutoff = 0
        self.touched = False
        self.len_cache = 0
        self.cut_cache = 0
        self.base: list[tuple[int, bytes]] = []
        self.base_valid = True  # new rows have an empty drained part
        self.memo: set[tuple[int, bytes]] = set()
        self.memo_valid = False
        self.memo_plen = 0
        self.memo_cut = 0
        self.gen = 0
        self.delta: set[tuple[int, bytes]] = set()
        self.delta_cutoff = 0
        self.delta_present = False


class PyTlogTable:
    __slots__ = (
        "_keys", "_rkeys", "_rows", "_pend_rows_count", "_row_overdue",
        "_delta_rows", "_touched", "_live_total", "_sync_dirty",
    )

    def __init__(self):
        self._keys: dict[bytes, int] = {}
        self._rkeys: list[bytes] = []
        self._rows: list[_Row] = []
        self._pend_rows_count = 0
        self._row_overdue = False
        self._delta_rows: list[int] = []
        self._touched: list[int] = []  # rows with pend or pend_cutoff
        self._live_total = 0  # sum of len_cache over all rows
        self._sync_dirty: dict[int, None] = {}  # since last digest pass

    # -- keys ---------------------------------------------------------------

    def rows(self) -> int:
        return len(self._rkeys)

    def upsert(self, key: bytes) -> int:
        row = self._keys.get(key)
        if row is None:
            row = len(self._rkeys)
            self._keys[key] = row
            self._rkeys.append(key)
            self._rows.append(_Row())
        return row

    def find(self, key: bytes) -> int:
        return self._keys.get(key, -1)

    def key_of(self, row: int) -> bytes:
        return self._rkeys[row]

    # -- view math ------------------------------------------------------------

    def cutoff_view(self, row: int) -> int:
        r = self._rows[row]
        return max(r.pend_cutoff, r.cut_cache)

    def quiescent(self, row: int) -> bool:
        r = self._rows[row]
        return not r.pend and r.pend_cutoff <= r.cut_cache

    def _memo_current(self, r: _Row) -> bool:
        return (
            r.memo_valid
            and r.memo_plen == len(r.pend)
            and r.memo_cut == max(r.pend_cutoff, r.cut_cache)
        )

    def _touch(self, r: _Row, row: int) -> None:
        if not r.touched:
            r.touched = True
            self._touched.append(row)
        self._sync_dirty[row] = None

    def _append_pend(self, r: _Row, row: int, e: tuple[int, bytes]) -> None:
        if not r.pend:
            self._pend_rows_count += 1
        r.pend.append(e)
        self._touch(r, row)
        if len(r.pend) >= ROW_DRAIN_THRESHOLD:
            self._row_overdue = True

    # -- mutations ------------------------------------------------------------

    def ins(self, row: int, ts: int, value: bytes) -> None:
        r = self._rows[row]
        e = (ts, value)
        self._append_pend(r, row, e)
        r.gen += 1
        if r.memo_valid:
            cut = max(r.pend_cutoff, r.cut_cache)
            if r.memo_plen != len(r.pend) - 1 or r.memo_cut != cut:
                r.memo_valid = False
                r.memo = set()  # free, don't retain dead sets
            else:
                if ts >= cut:
                    r.memo.add(e)
                r.memo_plen = len(r.pend)
                r.memo_cut = cut
        if ts >= r.cut_cache:
            if not r.delta_present:
                r.delta_present = True
                self._delta_rows.append(row)
            if ts >= r.delta_cutoff:
                r.delta.add(e)

    def converge_entry(self, row: int, ts: int, value: bytes) -> None:
        r = self._rows[row]
        self._append_pend(r, row, (ts, value))
        r.gen += 1

    def converge_cutoff(self, row: int, c: int) -> None:
        r = self._rows[row]
        if c > r.pend_cutoff:
            r.pend_cutoff = c
            self._touch(r, row)
            r.gen += 1

    # -- the merged serving view ----------------------------------------------

    def size(self, row: int) -> int:
        r = self._rows[row]
        if self.quiescent(row):
            return r.len_cache
        if self._memo_current(r):
            return len(r.memo)
        if not r.base_valid:
            return -1
        cut = max(r.pend_cutoff, r.cut_cache)
        r.memo = {e for e in r.base if e[0] >= cut}
        r.memo.update(e for e in r.pend if e[0] >= cut)
        r.memo_valid = True
        r.memo_plen = len(r.pend)
        r.memo_cut = cut
        r.gen += 1
        return len(r.memo)

    def merged_entries(self, row: int):
        r = self._rows[row]
        if self._memo_current(r):
            return list(r.memo)
        if self.quiescent(row) and r.base_valid:
            return list(r.base)
        return None

    def base_entries(self, row: int):
        """The drained row content when the carried base is valid; None
        when the repo must gather it from the device."""
        r = self._rows[row]
        return list(r.base) if r.base_valid else None

    def base_valid(self, row: int) -> bool:
        return self._rows[row].base_valid

    def live_total(self) -> int:
        return self._live_total

    def export_sync_dirty(self) -> list[int]:
        rows = list(self._sync_dirty)
        self._sync_dirty.clear()
        return rows

    def compact_values(self) -> bool:
        return False  # raw bytes, freed with their entries: nothing interned

    def set_base(self, row: int, entries) -> None:
        r = self._rows[row]
        r.base = list(entries)
        r.base_valid = True
        r.memo_valid = False
        r.memo = set()
        r.gen += 1

    # -- drain plumbing -------------------------------------------------------

    def len_cache(self, row: int) -> int:
        return self._rows[row].len_cache

    def cut_cache(self, row: int) -> int:
        return self._rows[row].cut_cache

    def pend_cutoff(self, row: int) -> int:
        return self._rows[row].pend_cutoff

    def gen(self, row: int) -> int:
        return self._rows[row].gen

    def pend_len(self, row: int) -> int:
        return len(self._rows[row].pend)

    def pend_rows_count(self) -> int:
        return self._pend_rows_count

    def row_overdue(self) -> bool:
        return self._row_overdue

    def touched_rows(self) -> list[int]:
        return list(self._touched)

    def touched_count(self) -> int:
        return len(self._touched)

    def export_pend(self, row: int) -> list[tuple[int, bytes]]:
        return list(self._rows[row].pend)

    def export_pend_bulk(self, rows: list[int]):
        return {r: list(self._rows[r].pend) for r in rows}

    def finish_row(self, row: int, length: int, cut: int) -> None:
        r = self._rows[row]
        if self._memo_current(r):
            r.base = [e for e in r.memo if e[0] >= cut]
            r.base_valid = len(r.base) == length
        else:
            r.base = []
            r.base_valid = length == 0
        self._sync_dirty[row] = None  # a fused trim can change the view
        self._live_total += int(length) - r.len_cache
        r.len_cache = int(length)
        r.cut_cache = int(cut)
        if r.pend:
            self._pend_rows_count -= 1
        r.pend = []
        r.pend_cutoff = 0
        if r.base_valid:
            r.memo = set(r.base)
            r.memo_valid = True
            r.memo_plen = 0
            r.memo_cut = max(r.pend_cutoff, r.cut_cache)
        else:
            r.memo_valid = False
            r.memo = set()
        r.gen += 1

    def finish_drain_end(self) -> None:
        for row in self._touched:
            r = self._rows[row]
            r.touched = False
            if r.pend:  # touched but outside the drain set: cannot happen
                r.pend = []  # under the repo lock; mirror the global clear
                r.memo_valid = False
                r.gen += 1
            r.pend_cutoff = 0
        self._touched.clear()
        self._pend_rows_count = 0
        self._row_overdue = False

    # -- outbound deltas ------------------------------------------------------

    def deltas_size(self) -> int:
        return len(self._delta_rows)

    def delta_raise_cutoff(self, row: int, c: int) -> None:
        r = self._rows[row]
        if not r.delta_present:
            r.delta_present = True
            self._delta_rows.append(row)
        if c > r.delta_cutoff:
            r.delta_cutoff = c
            r.delta = {e for e in r.delta if e[0] >= c}

    def flush_deltas(self):
        out = []
        for row in self._delta_rows:
            r = self._rows[row]
            ents = sorted(r.delta, reverse=True)
            out.append(
                (
                    self._rkeys[row],
                    ([(v, t) for t, v in ents], r.delta_cutoff),
                )
            )
            r.delta = set()
            r.delta_cutoff = 0
            r.delta_present = False
        self._delta_rows.clear()
        out.sort()
        return out


class NativeTlogTable:
    """The TLOG view over a shared native serving engine."""

    __slots__ = ("_eng",)

    def __init__(self, engine):
        self._eng = engine

    def rows(self) -> int:
        return self._eng.tlog_rows()

    def upsert(self, key: bytes) -> int:
        return self._eng.tlog_upsert(key)

    def find(self, key: bytes) -> int:
        return self._eng.tlog_find(key)

    def key_of(self, row: int) -> bytes:
        return self._eng.tlog_key_of(row)

    def cutoff_view(self, row: int) -> int:
        return self._eng.tlog_cutoff_view(row)

    def quiescent(self, row: int) -> bool:
        return self._eng.tlog_quiescent(row)

    def ins(self, row: int, ts: int, value: bytes) -> None:
        self._eng.tlog_ins(row, ts, value)

    def converge_entry(self, row: int, ts: int, value: bytes) -> None:
        self._eng.tlog_conv_entry(row, ts, value)

    def converge_cutoff(self, row: int, c: int) -> None:
        self._eng.tlog_conv_cutoff(row, c)

    def size(self, row: int) -> int:
        return self._eng.tlog_size(row)

    def merged_entries(self, row: int):
        return self._eng.tlog_merged_entries(row)

    def base_entries(self, row: int):
        return self._eng.tlog_base_entries(row)

    def base_valid(self, row: int) -> bool:
        return self._eng.tlog_base_valid(row)

    def live_total(self) -> int:
        return self._eng.tlog_live_total()

    def export_sync_dirty(self) -> list[int]:
        return self._eng.tlog_export_sync_dirty()

    def compact_values(self) -> bool:
        return self._eng.tlog_compact()

    def set_base(self, row: int, entries) -> None:
        self._eng.tlog_set_base(row, entries)

    def len_cache(self, row: int) -> int:
        return self._eng.tlog_len_cache(row)

    def cut_cache(self, row: int) -> int:
        return self._eng.tlog_cut_cache(row)

    def pend_cutoff(self, row: int) -> int:
        return self._eng.tlog_pend_cutoff(row)

    def gen(self, row: int) -> int:
        return self._eng.tlog_gen(row)

    def pend_len(self, row: int) -> int:
        return self._eng.tlog_pend_len(row)

    def pend_rows_count(self) -> int:
        return self._eng.tlog_pend_rows_count()

    def row_overdue(self) -> bool:
        return self._eng.tlog_row_overdue()

    def touched_rows(self) -> list[int]:
        return self._eng.tlog_touched_rows()

    def touched_count(self) -> int:
        return self._eng.tlog_touched_count()

    def export_pend(self, row: int) -> list[tuple[int, bytes]]:
        return self._eng.tlog_export_pend(row)

    def export_pend_bulk(self, rows: list[int]):
        return self._eng.tlog_export_pend_bulk(rows)

    def finish_row(self, row: int, length: int, cut: int) -> None:
        self._eng.tlog_finish_row(row, int(length), int(cut))

    def finish_drain_end(self) -> None:
        self._eng.tlog_finish_end()

    def deltas_size(self) -> int:
        return self._eng.tlog_deltas_size()

    def delta_raise_cutoff(self, row: int, c: int) -> None:
        self._eng.tlog_delta_raise_cutoff(row, c)

    def flush_deltas(self):
        return self._eng.tlog_flush_deltas()
