"""TENSOR repo: device-mirrored tensor-valued register keyspace.

The first repo whose values are tensors (ROADMAP item 3): each key
holds a fixed-dim f32 vector whose join is per-coordinate MAX,
per-coordinate LWW (replica-id tiebreak), or timestamp-weighted AVG —
the ops/tensor_host.py lattice. No reference analog exists (jylis has
no tensor type); the semantics follow arXiv:2605.19373 /
arXiv:2607.01308.

Serving posture is observe-first (the TREG/counters discipline): GET
joins the drained cache with the pending window entirely host-side —
an O(dim) compare, never a device round-trip — while SET/MRG and
incoming cluster deltas coalesce per key in the host table and drain
to the device mirror in one fused gather->vmap-join->scatter batch
when the pending window trips the threshold. The mirror is where
thousands of vector merges collapse into one XLA launch
(ops/tensor.py; the `tensor-merge` bench drives the same kernels at
the 1M-key x 64-dim x 64-replica shape).

Device row mapping: one row per MAX/LWW key; one row per (key,
contributing replica) for AVG keys — so all three merge modes drain
through the ONE vmap'd (ts, rid, okey) select kernel. The rid plane is
the low 32 bits of the contributor id (mirror-only narrowing: the host
lattice keeps full ints and is the serving truth).

Delta wire shape: an ops/tensor_host.Tensor (full joinable state,
delta-state style — cluster/codec.py delta/TENSOR).
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np

from ..ops import tensor
from ..ops.tensor_host import (
    MODE_AVG,
    MODE_LWW,
    MODE_MAX,
    MODE_NAMES,
    MODES_BY_NAME,
    Tensor,
)
from .base import ParseError, bucket, need, pad_rows, parse_u64
from .help import RepoHelp
from .tensor_table import PyTensorTable
from ..utils.metrics import timed_drain

TENSOR_HELP = RepoHelp(
    "TENSOR",
    {
        "GET": "key",
        "SET": "key mode timestamp vector",
        "MRG": "key delta",
    },
)

# pending writes/deltas flush to the device mirror once they pile this
# high; GETs never need the drain (host winner join), so this bounds
# host-window size while keeping device batches large. Lower than
# TREG's 4096: each row is a whole vector, not a scalar.
PENDING_DRAIN_THRESHOLD = 1024

BADSHAPE = (
    "BADSHAPE (tensor payload must be a non-empty multiple of 4 bytes: "
    "packed little-endian float32)"
)


@partial(jax.jit, donate_argnums=0)
def _drain(state, ki, d_val, d_ts_hi, d_ts_lo, d_rid):
    return tensor.converge_batch(state, ki, d_val, d_ts_hi, d_ts_lo, d_rid)


class RepoTENSOR:
    name = "TENSOR"
    help = TENSOR_HELP

    def __init__(self, identity: int, row_cap: int = 1024, engine="auto"):
        # engine accepted for the Database constructor's uniform call
        # shape; TENSOR has no native table (the engine defers unknown
        # first words), so the Python table is always the truth
        self._identity = identity
        self._tbl = PyTensorTable()
        self._row_cap = row_cap
        self._dim_cap = 8
        self._state = tensor.init(self._row_cap, self._dim_cap)
        # device rows per table row: {contributor: device row} —
        # contributor is -1 for the single MAX/LWW row, the AVG replica
        # id otherwise (keyed by row so a dominance-flip retirement is
        # O(that row's contributions), not a scan of every device row)
        self._dev_rows: dict[int, dict[int, int]] = {}
        # monotone row allocator: retired rows (dominance flips) are
        # never reused — a reused id would inherit the old rank's planes
        self._next_dev = 0
        # per-AVG-device-row monotone version stamp (see drain)
        self._avg_ver: dict[int, int] = {}
        # last-mirrored (mode, dim) per table row: a dominance flip
        # (replication can upgrade a key's rank wholesale) retires the
        # row's device rows — the old planes hold another lattice's
        # bits, which the monotone select could never regress past
        self._row_stamp: dict[int, tuple[int, int]] = {}

    # -- commands ------------------------------------------------------------

    def apply(self, resp, args: list[bytes]) -> bool:
        op = need(args, 0)
        if op == b"GET":
            row = self._tbl.find(need(args, 1))
            w = self._tbl.winner(row) if row >= 0 else None
            rendered = w.read() if w is not None else None
            if rendered is None:
                resp.null()
            else:
                vec, ts = rendered
                resp.array_start(3)
                resp.string(MODE_NAMES[w.mode])
                resp.string(vec)
                resp.u64(ts)
            return False
        if op == b"SET":
            key = need(args, 1)
            mode = MODES_BY_NAME.get(need(args, 2))
            if mode is None:
                raise ParseError()
            ts = parse_u64(need(args, 3))
            payload = need(args, 4)
            if not payload or len(payload) % 4:
                resp.err(BADSHAPE)
                return False
            if mode == MODE_MAX:
                delta = Tensor.max_value(payload)
            elif mode == MODE_LWW:
                delta = Tensor.lww(payload, ts, self._identity & 0xFFFFFFFF)
            else:
                delta = Tensor.avg(self._identity, ts, payload)
            return self._admit(resp, key, delta)
        if op == b"MRG":
            # client-side anti-entropy: the payload is one canonical
            # wire delta (cluster/codec.py delta/TENSOR bytes) — merge
            # an externally-computed tensor state into the key
            from ..cluster import codec

            key = need(args, 1)
            try:
                delta = codec.decode_delta("TENSOR", need(args, 2))
            except codec.CodecError:
                resp.err(
                    "BADPAYLOAD (MRG payload must be a canonical "
                    "delta/TENSOR encoding)"
                )
                return False
            if delta.mode == 0:
                resp.err("BADPAYLOAD (empty tensor delta)")
                return False
            return self._admit(resp, key, delta)
        raise ParseError()

    def _admit(self, resp, key: bytes, delta: Tensor) -> bool:
        """The RESP boundary's mode/dim gate: a client write whose
        (mode, dim) stamp disagrees with the key's is REJECTED here —
        only replication paths exercise the lattice's dominance rule."""
        row = self._tbl.find(key)
        if row >= 0:
            stamp = self._tbl.stamp(row)
            if stamp is not None and stamp != (delta.mode, delta.dim):
                cur_m, cur_d = stamp
                resp.err(
                    "BADSHAPE (key holds %s/%d, write is %s/%d)"
                    % (
                        MODE_NAMES[cur_m].decode(),
                        cur_d,
                        MODE_NAMES[delta.mode].decode(),
                        delta.dim,
                    )
                )
                return False
        row = self._tbl.upsert(key)
        self._tbl.write(row, delta)
        self._tbl.note_delta(row, delta)
        if self._tbl.pend_count() >= PENDING_DRAIN_THRESHOLD:
            self.drain()
        resp.ok()
        return True

    # -- lattice plumbing ----------------------------------------------------

    def converge(self, key: bytes, delta: Tensor) -> None:
        # buffer only: the serving path drains via drain_overdue in a
        # worker thread; sync callers (snapshot restore) drain explicitly
        self._tbl.write(self._tbl.upsert(key), delta)

    def deltas_size(self) -> int:
        return self._tbl.deltas_size()

    def flush_deltas(self):
        return self._tbl.flush_deltas()

    def may_drain(self, args: list[bytes]) -> bool:
        """GET never drains (host winner join); a SET/MRG may trigger
        the threshold drain, which the server offloads to a thread."""
        return (
            bool(args)
            and args[0] in (b"SET", b"MRG")
            and self._tbl.pend_count() + 1 >= PENDING_DRAIN_THRESHOLD
        )

    def drain_overdue(self) -> bool:
        return self._tbl.pend_count() >= PENDING_DRAIN_THRESHOLD

    # -- sync digest (cluster/syncdigest.py) ---------------------------------

    def sync_dirty_keys(self) -> list[bytes]:
        return [self._tbl.key_of(r) for r in self._tbl.export_sync_dirty()]

    def sync_canon(self, key: bytes) -> bytes | None:
        row = self._tbl.find(key)
        w = self._tbl.winner(row) if row >= 0 else None
        if w is None or w.mode == 0:
            return None
        return repr(w.canon()).encode()

    # -- snapshot (persist.py): full state in the wire-delta shape ----------

    def dump_state(self):
        # host truth IS the join the device converges to; the drain just
        # keeps the mirror caught up before the dump snapshot point
        self.drain()
        return self._tbl.dump()

    def load_state(self, batch) -> None:
        for key, delta in batch:
            self.converge(key, delta)

    # -- device drain --------------------------------------------------------

    def _dev_row(self, row: int, contrib: int) -> int:
        m = self._dev_rows.setdefault(row, {})
        dev = m.get(contrib)
        if dev is None:
            dev = self._next_dev
            self._next_dev += 1
            m[contrib] = dev
        return dev

    @timed_drain("TENSOR", lambda self: self._tbl.pend_count())
    def drain(self) -> None:
        pend = self._tbl.export_pend()
        if not pend:
            return
        # expand to device rows FIRST (capacity growth must see the
        # post-expansion row count and the batch's widest vector). Every
        # plane mirrors the table WINNER (cache ⊔ pending), never the
        # bare pending delta: a stale remote delta in the window must
        # not regress the mirror below the host truth.
        entries: list[tuple[int, Tensor, int]] = []  # dev, winner, rid
        max_dim = 1
        for row, t in pend:
            w = self._tbl.winner(row)
            if w is None or w.mode == 0:
                continue
            max_dim = max(max_dim, w.dim)
            stamp = (w.mode, w.dim)
            prev = self._row_stamp.get(row)
            if prev is not None and prev != stamp:
                # dominance flip: abandon every device row this table
                # row ever mapped to (fresh rows start at the identity,
                # so the new-rank winner lands exactly; the orphaned
                # rows are garbage bounded by the flip count)
                for dev in self._dev_rows.pop(row, {}).values():
                    self._avg_ver.pop(dev, None)
            self._row_stamp[row] = stamp
            if w.mode == MODE_AVG:
                rids = (
                    sorted(t.contribs)
                    if t.mode == MODE_AVG and t.dim == w.dim and prev == stamp
                    else sorted(w.contribs)  # flip/fresh: re-mirror all
                )
                for rid in rids:
                    if rid in w.contribs:
                        entries.append((self._dev_row(row, rid), w, rid))
            else:
                entries.append((self._dev_row(row, -1), w, -1))
        self._grow_to_fit(max_dim)
        if not entries:
            self._tbl.fold_pend()
            return
        b = bucket(len(entries))
        d = self._dim_cap
        ki = pad_rows(b)
        d_val = np.full((b, d), tensor.BOTTOM_BITS, np.uint32)
        d_ts = np.zeros((b, d), np.uint64)
        d_rid = np.zeros((b, d), np.uint32)
        for i, (dev, w, contrib) in enumerate(entries):
            ki[i] = dev
            dim = w.dim
            if w.mode == MODE_AVG:
                # an AVG contribution row mirrors the host's whole-vector
                # winner for (key, rid): the host joins same-rid
                # contributions as whole vectors (lexicographic
                # (ts, okey-tuple)), which a per-coordinate select cannot
                # reproduce at equal-ts ties — so the ts planes carry a
                # LOCAL monotone version stamp, making the select
                # degenerate to take-latest-host-winner. The mirror
                # reflects this node's converged truth; cross-replica
                # convergence already happened in the host join.
                _cts, vec = w.contribs[contrib]
                ver = self._avg_ver.get(dev, 0) + 1
                self._avg_ver[dev] = ver
                d_val[i, :dim] = np.frombuffer(vec, "<u4")
                d_ts[i, :dim] = ver
                d_rid[i, :dim] = contrib & 0xFFFFFFFF
            else:
                # MAX/LWW winners are per-coordinate monotone across
                # drains WITHIN one (mode, dim) rank — flips retire the
                # row above — so the device join lands exactly the winner
                d_val[i, :dim] = np.frombuffer(w.val, "<u4")
                if w.mode == MODE_LWW:
                    d_ts[i, :dim] = np.frombuffer(w.ts, "<u8")
                    d_rid[i, :dim] = np.frombuffer(w.rid, "<u4")
        ts_hi = (d_ts >> np.uint64(32)).astype(np.uint32)
        ts_lo = d_ts.astype(np.uint32)
        self._state = _drain(self._state, ki, d_val, ts_hi, ts_lo, d_rid)
        self._tbl.fold_pend()

    def _grow_to_fit(self, max_dim: int) -> None:
        rows = bucket(max(self._next_dev, 1), self._row_cap)
        dim = bucket(max_dim, self._dim_cap)
        if (rows, dim) != (self._row_cap, self._dim_cap):
            self._row_cap, self._dim_cap = rows, dim
            self._state = tensor.grow(self._state, rows, dim)
