"""BCOUNT repo: bounded escrow counters (ops/bcount.py) per key.

ROADMAP item 4's second half — the inventory / rate-limit / quota
workload: a counter that must respect ``0 ≤ value ≤ bound`` under
write contention without coordinating writes. The lattice and the
escrow-safety argument live in ops/bcount.py; this repo is the RESP
surface, the full-view delta flush (a BCOUNT delta always ships the
replica's complete per-key state so every shipped state is
self-justifying under join), converge buffering with a timed host
drain, per-key digest entries, and snapshot dump/load.

RESP surface:

    BCOUNT GRANT key amount            raise the bound; the granting
                                       replica receives the inc-escrow
    BCOUNT INC key amount              spend inc-escrow (value +n)
    BCOUNT DEC key amount              spend dec-escrow (value -n)
    BCOUNT TRANSFER key to_rid amount [INC|DEC]
                                       move own escrow to replica
                                       to_rid (default DEC-escrow)
    BCOUNT GET key                     -> [value, bound]

INC / DEC / TRANSFER refuse with the typed ``OUTOFBOUND`` error when
the replica's local escrow cannot fund the operation — the documented
price of coordination-free bounded writes (transfer escrow in, or
retry on a replica that holds some).

Delta wire shape: the five-component full view
``(grants, incs, decs, xi, xd)`` — see delta/BCOUNT in the schema.
"""

from __future__ import annotations

from ..ops.bcount import BCount
from ..utils.metrics import timed_drain
from .base import ParseError, need, parse_u64
from .help import RepoHelp

BCOUNT_HELP = RepoHelp(
    "BCOUNT",
    {
        "GET": "key",
        "GRANT": "key amount",
        "INC": "key amount",
        "DEC": "key amount",
        "TRANSFER": "key to_replica amount [INC|DEC]",
    },
)

PENDING_DRAIN_THRESHOLD = 512


def outofbound(resp, what: str, rights: int, amount: int) -> None:
    resp.err(
        f"OUTOFBOUND (insufficient local {what} escrow: rights {rights} "
        f"< amount {amount}; transfer escrow to this replica or retry "
        "on one that holds some)"
    )


class RepoBCOUNT:
    name = "BCOUNT"
    help = BCOUNT_HELP

    def __init__(self, identity: int, engine=None, **_kw):
        # engine accepted for constructor parity; BCOUNT is python-only
        self._identity = identity
        self._keys: dict[bytes, BCount] = {}
        self._dirty: set[bytes] = set()
        self._sync_dirty: set[bytes] = set()
        self._pending: list[tuple[bytes, tuple]] = []

    def _for(self, key: bytes) -> BCount:
        bc = self._keys.get(key)
        if bc is None:
            bc = BCount()
            self._keys[key] = bc
        return bc

    def _note(self, key: bytes) -> None:
        self._dirty.add(key)
        self._sync_dirty.add(key)

    # -- commands ------------------------------------------------------------

    def apply(self, resp, args: list[bytes]) -> bool:
        op = need(args, 0)
        if op == b"GET":
            if self._pending:
                self.drain()
            key = need(args, 1)
            bc = self._keys.get(key)
            value = bc.value() if bc is not None else 0
            bound = bc.bound() if bc is not None else 0
            resp.array_start(2)
            resp.i64(value)  # the invariant pins value >= 0; i64 keeps
            resp.u64(bound)  # even a hostile loaded state renderable
            return False
        if op == b"GRANT":
            key = need(args, 1)
            amount = parse_u64(need(args, 2))
            if self._pending:
                self.drain()
            bc = self._for(key)
            if not bc.grant(self._identity, amount):
                # this replica's grant cell would pass u64 — the wire
                # span's ceiling (every decoder would refuse the delta)
                resp.err(
                    "OUTOFBOUND (grant overflows this replica's u64 "
                    f"grant cell: {bc.grants.get(self._identity, 0)} "
                    f"+ {amount})"
                )
                return False
            self._note(key)
            resp.ok()
            return True
        if op in (b"INC", b"DEC"):
            key = need(args, 1)
            amount = parse_u64(need(args, 2))
            if self._pending:
                # buffered foreign escrow may fund this spend: fold it
                # in before computing rights (refusals stay local-view
                # sound either way — rights only grow with knowledge)
                self.drain()
            bc = self._for(key)
            if op == b"INC":
                if not bc.inc(self._identity, amount):
                    outofbound(resp, "inc", bc.inc_rights(self._identity),
                               amount)
                    return False
            else:
                if not bc.dec(self._identity, amount):
                    outofbound(resp, "dec", bc.dec_rights(self._identity),
                               amount)
                    return False
            self._note(key)
            resp.ok()
            return True
        if op == b"TRANSFER":
            key = need(args, 1)
            to_rid = parse_u64(need(args, 2))
            amount = parse_u64(need(args, 3))
            pol = b"DEC"
            if len(args) > 4:
                pol = need(args, 4)
                if pol not in (b"INC", b"DEC"):
                    raise ParseError()
            if self._pending:
                self.drain()
            bc = self._for(key)
            polarity = "INC" if pol == b"INC" else "DEC"
            if not bc.transfer(self._identity, to_rid, amount, polarity):
                rights = (
                    bc.inc_rights(self._identity) if polarity == "INC"
                    else bc.dec_rights(self._identity)
                )
                outofbound(resp, polarity.lower(), rights, amount)
                return False
            self._note(key)
            resp.ok()
            return True
        raise ParseError()

    # -- lattice plumbing ----------------------------------------------------

    def converge(self, key: bytes, delta: tuple) -> None:
        self._pending.append((key, delta))

    def drain_overdue(self) -> bool:
        return len(self._pending) >= PENDING_DRAIN_THRESHOLD

    @timed_drain("BCOUNT", lambda self: len(self._pending))
    def drain(self) -> None:
        pending, self._pending = self._pending, []
        for key, delta in pending:
            self._for(key).converge(BCount.from_wire(delta))
            self._sync_dirty.add(key)

    def deltas_size(self) -> int:
        return len(self._dirty)

    def flush_deltas(self):
        if self._pending:
            self.drain()
        out = []
        for key in sorted(self._dirty):
            bc = self._keys.get(key)
            if bc is not None and not bc.is_bottom():
                out.append((key, bc.to_wire()))
        self._dirty.clear()
        return out

    # -- sync digest (models/database.py incremental tree) -------------------

    def sync_prepare(self) -> None:
        if self._pending:
            self.drain()

    def sync_dirty_keys(self) -> list[bytes]:
        out = sorted(self._sync_dirty)
        self._sync_dirty.clear()
        return out

    def sync_canon(self, key: bytes) -> bytes | None:
        bc = self._keys.get(key)
        if bc is None or bc.is_bottom():
            return None
        return repr(bc.canon()).encode()

    # -- snapshot (persist.py): full state in the wire-delta shape ----------

    def dump_state(self):
        if self._pending:
            self.drain()
        return [
            (key, bc.to_wire())
            for key, bc in sorted(self._keys.items())
            if not bc.is_bottom()
        ]

    def load_state(self, batch) -> None:
        for key, delta in batch:
            self.converge(key, delta)
        self.drain()

    # -- direct host views (tests / bench / jmodel) --------------------------

    def counter(self, key: bytes) -> BCount | None:
        if self._pending:
            self.drain()
        return self._keys.get(key)
