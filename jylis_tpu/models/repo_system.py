"""SYSTEM repo: the replicated server log.

Reference analog: repo_system.pony:13-64. One TLog under the pseudo-key
"_log"; GETLOG [count] reads it; the server itself appends via inslog()
with wall-clock milliseconds (the only server-minted timestamps in the
system, repo_system.pony:41-43) and trims via trimlog(). deltas_size() is
hard-wired to 1, so the system-log delta ships on every heartbeat even when
empty — a reference quirk we reproduce because peers rely on the periodic
converge+Pong traffic it generates.

The log is tiny (trimmed to ~200 entries) and host-resident by design; a
device round-trip per log line would be absurd (SURVEY.md section 2.6).
"""

from __future__ import annotations

import time

from ..ops import hostref
from .base import ParseError, need, parse_opt_count
from .help import LeafHelp

SYSTEM_HELP = LeafHelp(
    "The following are valid SYSTEM commands:\n"
    "  SYSTEM GETLOG [count]\n"
    "  SYSTEM METRICS\n"
    "  SYSTEM LATENCY [WINDOW seconds]\n"
    "  SYSTEM OBSERVE\n"
    "  SYSTEM TRACE [count]\n"
    "  SYSTEM TRACE SPANS\n"
    "  SYSTEM DIGEST [TYPES]\n"
    "  SYSTEM TOPOLOGY\n"
    "  SYSTEM VERSION"
)


def _now_millis() -> int:
    return time.time_ns() // 1_000_000


class RepoSYSTEM:
    name = "SYSTEM"
    help = SYSTEM_HELP

    def __init__(self, identity: int):
        self._identity = identity
        self._log = hostref.TLog()
        self._delta = hostref.TLog()
        # Database wires this to its per-instance commands-served totals
        # (Python dispatch + native engine) for METRICS' "cmds" lines
        self.served_fn = None
        # ... and this to the native-vs-demoted serving split for the
        # SERVING native_cmds/demoted_cmds/demotions/fallback_frac lines
        self.serving_fn = None
        # the Cluster wires this to its peer-lifecycle totals for the
        # CLUSTER section (peer states, dials/fails, evictions by
        # reason, sync served/deferred, held-delta drops)
        self.cluster_fn = None
        # the Database wires this to its SessionIndex's counters for
        # the SESSION section (tokens minted, STALE/BADTOKEN refusals,
        # adoption events — docs/sessions.md)
        self.session_fn = None
        # ... and this to its per-peer convergence-lag view (push→apply
        # EWMA per sender) for the SYSTEM LATENCY per-peer lines
        self.lag_fn = None
        # the owning Database's MetricsRegistry (obs/registry.py):
        # drain/journal counters, latency histograms, trace ring —
        # wired as `metrics` like every repo. None (a standalone
        # RepoSYSTEM) reads the process DEFAULT via resolve_registry.
        self.metrics = None
        # main.py wires this on lane workers: {"id": k, "count": n} for
        # the LANE section of SYSTEM METRICS (which lane a connection
        # landed on); None on single-lane nodes — no section
        self.lane_fn = None
        # the owning Database wires this to its single-threaded digest
        # computation (the async serving path intercepts SYSTEM DIGEST
        # in Database.apply_async instead — it must await repo locks)
        self.digest_fn = None
        # ... and this to the per-type breakdown (SYSTEM DIGEST TYPES):
        # [(name, 32-byte digest)] so operators localize divergence to a
        # type before walking its digest-tree ranges
        self.digest_types_fn = None
        # the Database wires this to its AdmissionController's totals
        # for the OVERLOAD section of SYSTEM METRICS (declared overload
        # state, enter/exit transitions, per-class shed counters —
        # docs/operations.md, "Overload")
        self.overload_fn = None
        # the Cluster wires this to its topology view (self + every
        # known address with region/liveness/bridge attribution): the
        # SYSTEM TOPOLOGY reply cluster-aware clients (client.py
        # ClusterClient) discover routing from
        self.topology_fn = None

    def apply(self, resp, args: list[bytes]) -> bool:
        op = need(args, 0)
        if op == b"GETLOG":
            count = parse_opt_count(args, 1)
            n = min(count, self._log.size())
            resp.array_start(n)
            for value, ts in self._log.latest(n):
                resp.array_start(2)
                resp.string(value)
                resp.u64(ts)
            return False
        if op == b"METRICS":
            # live serving + merge-path metrics (extension — the
            # reference has no metrics surface at all): one "name key
            # value" line per counter, flat and greppable from any Redis
            # client. "cmds" counts commands served on BOTH paths
            # (native engine + Python); drains/keys/device_ms cover the
            # device merge path
            from ..utils.metrics import metric_lines

            lines = metric_lines(
                self.served_fn() if self.served_fn else None,
                self.serving_fn() if self.serving_fn else None,
                self.cluster_fn() if self.cluster_fn else None,
                registry=self.metrics,
                lane=self.lane_fn() if self.lane_fn else None,
                session=self.session_fn() if self.session_fn else None,
                overload=self.overload_fn() if self.overload_fn else None,
            )
            resp.array_start(len(lines))
            for line in lines:
                resp.string(line)
            return False
        if op == b"LATENCY" and len(args) > 1 and args[1] == b"WINDOW":
            # windowed quantiles: subtract the deposited mark closest to
            # <seconds> ago from the live buckets, so a fresh regression
            # on a long-running node is not drowned by since-boot
            # history. Marks deposit opportunistically on every scrape /
            # LATENCY call (rate-limited in the registry) — the first
            # WINDOW query after boot may report "no window yet".
            try:
                want_s = float(need(args, 2))
            except ValueError:
                raise ParseError() from None
            if want_s <= 0:
                raise ParseError()
            reg = self._registry()
            reg.window_deposit()
            achieved, stats = reg.window_stats(want_s)
            if stats is None:
                resp.array_start(1)
                resp.string(b"no window yet (no mark deposited)")
                return False
            lines = [f"window_s {achieved:.1f}"]
            for name, snap in stats:
                lines.append(
                    f"{name} count {snap['count']}"
                    f" p50_us {snap['p50_s'] * 1e6:.0f}"
                    f" p90_us {snap['p90_s'] * 1e6:.0f}"
                    f" p99_us {snap['p99_s'] * 1e6:.0f}"
                )
            resp.array_start(len(lines))
            for line in lines:
                resp.string(line)
            return False
        if op == b"LATENCY":
            # the latency histograms as one line per seam (count + p50/
            # p90/p99/max in µs), ALL declared seams — a zero count means
            # the seam exists but has not fired, which is itself signal —
            # plus one line per peer with the convergence-lag EWMA
            self._registry().window_deposit()  # feed LATENCY WINDOW
            lines = []
            for name, snap in self._registry().seam_stats():
                lines.append(
                    f"{name} count {snap['count']}"
                    f" p50_us {snap['p50_s'] * 1e6:.0f}"
                    f" p90_us {snap['p90_s'] * 1e6:.0f}"
                    f" p99_us {snap['p99_s'] * 1e6:.0f}"
                    f" max_us {snap['max_s'] * 1e6:.0f}"
                )
            if self.lag_fn is not None:
                for peer, ms in sorted(self.lag_fn().items()):
                    lines.append(f"converge_lag_ms peer {peer} {ms:.1f}")
            resp.array_start(len(lines))
            for line in lines:
                resp.string(line)
            return False
        if op == b"OBSERVE":
            # fleet-convergence + placement telemetry in one greppable
            # view: the --converge-slo-ms attainment fractions (from
            # sampled provenance spans, obs/jtrace.py) and the per-type
            # digest-tree write-heat concentration (manager.py _emit) —
            # which tree buckets absorb the write load, the signal a
            # future placement policy keys on
            reg = self._registry()
            lines = [
                f"converge sampled {reg.spans.sampled}"
                f" malformed {reg.spans.malformed}"
            ]
            for ms, frac, ok in reg.spans.slo_fracs():
                lines.append(f"converge_slo ms {ms} frac {frac:.4f} ok {ok}")
            for name in sorted(reg.write_heat):
                heat = reg.write_heat[name]
                total = sum(heat)
                top = sorted(
                    range(len(heat)), key=heat.__getitem__, reverse=True
                )[:4]
                hot = " ".join(f"{b}:{heat[b]}" for b in top if heat[b])
                lines.append(
                    f"write_heat {name} total {total} top {hot or '-'}"
                )
            resp.array_start(len(lines))
            for line in lines:
                resp.string(line)
            return False
        if op == b"TRACE" and len(args) > 1 and args[1] == b"SPANS":
            # the folded provenance-span view: sampled/malformed totals,
            # per-hop-transition and per-region-pair convergence-latency
            # quantiles, SLO attainment, and the worst-trace exemplar
            # chains (origin -> relay hops -> apply with per-hop offsets)
            lines = self._registry().spans.report_lines()
            resp.array_start(len(lines))
            for line in lines:
                resp.string(line)
            return False
        if op == b"TRACE":
            count = parse_opt_count(args, 1)
            entries = self._registry().trace.dump(count)
            resp.array_start(len(entries))
            from ..obs.trace import TraceRing

            for entry in entries:
                resp.string(TraceRing.format(entry))
            return False
        if op == b"DIGEST":
            # single-threaded path only (warmup/tests/direct drives):
            # the serving path's SYSTEM DIGEST [TYPES] is intercepted by
            # Database.apply_async, which awaits the repo locks
            if len(args) > 1 and args[1] == b"TYPES":
                if self.digest_types_fn is None:
                    raise ParseError()
                rows = self.digest_types_fn()
                resp.array_start(len(rows))
                for name, digest in rows:
                    resp.string(f"{name} {digest.hex()}".encode())
                return False
            if self.digest_fn is None:
                raise ParseError()
            resp.string(self.digest_fn().hex().encode())
            return False
        if op == b"TOPOLOGY":
            # the cluster-aware client's discovery surface: one line for
            # this node (advertised addr, region, bridge role, RESP
            # port) then one per known peer address with the observer's
            # own liveness evidence — enough to route to the nearest
            # replica and to notice a node leaving. Region-less /
            # cluster-less nodes report just themselves.
            if self.topology_fn is None:
                resp.array_start(1)
                resp.string(b"self - region - bridge 0 resp_port 0")
                return False
            lines = self.topology_fn()
            resp.array_start(len(lines))
            for line in lines:
                resp.string(line)
            return False
        if op == b"VERSION":
            from .. import __version__

            resp.string(f"jylis-tpu {__version__}".encode())
            return False
        raise ParseError()

    def _registry(self):
        from ..utils.metrics import resolve_registry

        return resolve_registry(self)

    # -- server-internal (repo_system.pony:56-64) --------------------------

    def inslog(self, line: str) -> None:
        ts = _now_millis()
        value = line.encode()
        self._log.insert(value, ts)
        self._delta.insert(value, ts)

    def trimlog(self, count: int) -> None:
        self._log.trim(count)

    # -- lattice plumbing ---------------------------------------------------

    def deltas_size(self) -> int:
        return 1  # quirk: always ship (repo_system.pony:21)

    def flush_deltas(self):
        out = [(b"_log", (self._delta.latest(), self._delta.cutoff))]
        self._delta = hostref.TLog()
        return out

    def converge(self, key: bytes, delta: tuple) -> None:
        if key != b"_log":
            return
        entries, cutoff = delta
        other = hostref.TLog(entries=list(entries), cutoff=cutoff)
        self._log.converge(other)

    # -- snapshot (persist.py): full state in the wire-delta shape ----------

    def dump_state(self):
        return [(b"_log", (self._log.latest(), self._log.cutoff))]

    def load_state(self, batch) -> None:
        for key, delta in batch:
            self.converge(key, delta)

    def drain(self) -> None:
        pass
