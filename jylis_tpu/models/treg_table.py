"""Host-state backends for the TREG repo.

TREG's host bookkeeping — key interning, the serving winner, the pending
drain window and the outbound delta accumulator — lives behind one small
table interface with two implementations (the counter_table.py pattern):

* `PyTregTable` — pure-Python dicts, the semantic oracle and the fallback
  when no C++ toolchain is available.
* `NativeTregTable` — a view over the native serving engine's TREG table
  (native/engine.h via native/engine.py). The same state the server's
  batch applier mutates, so commands applied natively and repo calls from
  Python see one source of truth.

The winner rule everywhere is lexicographic (ts, value-bytes) — the
reference's TReg last-writer-wins with value tiebreak
(repo_treg.pony:24-68). The winner is the join of the drained cache and
the pending window, so a drain never changes it: `fold_pend` just moves
the window into the cache.
"""

from __future__ import annotations


class PyTregTable:
    __slots__ = ("_keys", "_rkeys", "_cache", "_pending", "_deltas",
                 "_sync_dirty")

    def __init__(self):
        self._keys: dict[bytes, int] = {}
        self._rkeys: list[bytes] = []
        self._cache: dict[int, tuple[int, bytes]] = {}  # drained winner
        self._pending: dict[int, tuple[int, bytes]] = {}  # max since drain
        self._deltas: dict[int, tuple[int, bytes]] = {}  # max since flush
        self._sync_dirty: dict[int, None] = {}  # since last digest pass

    def rows(self) -> int:
        return len(self._rkeys)

    def upsert(self, key: bytes) -> int:
        row = self._keys.get(key)
        if row is None:
            row = len(self._rkeys)
            self._keys[key] = row
            self._rkeys.append(key)
        return row

    def find(self, key: bytes) -> int:
        return self._keys.get(key, -1)

    def key_of(self, row: int) -> bytes:
        return self._rkeys[row]

    def write(self, row: int, ts: int, value: bytes) -> None:
        self._sync_dirty[row] = None
        cur = self._pending.get(row)
        if cur is None or (ts, value) > cur:
            self._pending[row] = (ts, value)

    def note_delta(self, row: int, ts: int, value: bytes) -> None:
        cur = self._deltas.get(row)
        if cur is None or (ts, value) > cur:
            self._deltas[row] = (ts, value)

    def winner(self, row: int) -> tuple[int, bytes] | None:
        c = self._cache.get(row)
        p = self._pending.get(row)
        if c is None:
            return p
        if p is None:
            return c
        return max(c, p)

    def pend_count(self) -> int:
        return len(self._pending)

    def export_pend(self) -> list[tuple[int, int, bytes]]:
        return [(row, ts, v) for row, (ts, v) in self._pending.items()]

    def fold_pend(self) -> None:
        for row, p in self._pending.items():
            c = self._cache.get(row)
            if c is None or p > c:
                self._cache[row] = p
        self._pending.clear()

    def deltas_size(self) -> int:
        return len(self._deltas)

    def flush_deltas(self):
        out = sorted(
            (self._rkeys[row], (v, ts)) for row, (ts, v) in self._deltas.items()
        )
        self._deltas.clear()
        return out

    def dump(self):
        out = []
        for key, row in sorted(self._keys.items()):
            w = self.winner(row)
            if w is not None:
                out.append((key, (w[1], w[0])))
        return out

    def export_sync_dirty(self) -> list[int]:
        rows = list(self._sync_dirty)
        self._sync_dirty.clear()
        return rows


class NativeTregTable:
    """The TREG view over a shared native serving engine."""

    __slots__ = ("_eng",)

    def __init__(self, engine):
        self._eng = engine

    def rows(self) -> int:
        return self._eng.treg_rows()

    def upsert(self, key: bytes) -> int:
        return self._eng.treg_upsert(key)

    def find(self, key: bytes) -> int:
        return self._eng.treg_find(key)

    def key_of(self, row: int) -> bytes:
        return self._eng.treg_key_of(row)

    def write(self, row: int, ts: int, value: bytes) -> None:
        self._eng.treg_write(row, ts, value)

    def note_delta(self, row: int, ts: int, value: bytes) -> None:
        self._eng.treg_note_delta(row, ts, value)

    def winner(self, row: int) -> tuple[int, bytes] | None:
        return self._eng.treg_winner(row)

    def pend_count(self) -> int:
        return self._eng.treg_pend_count()

    def export_pend(self) -> list[tuple[int, int, bytes]]:
        return self._eng.treg_export_pend()

    def fold_pend(self) -> None:
        self._eng.treg_fold_pend()

    def deltas_size(self) -> int:
        return self._eng.treg_delta_count()

    def flush_deltas(self):
        return self._eng.treg_flush_deltas()

    def dump(self):
        out = []
        for row in range(self._eng.treg_rows()):
            w = self._eng.treg_winner(row)
            if w is not None:
                out.append((self._eng.treg_key_of(row), (w[1], w[0])))
        out.sort()
        return out

    def export_sync_dirty(self) -> list[int]:
        return self._eng.treg_export_sync_dirty()
