"""Database: keyspace router over the per-type repos.

Reference analog: database.pony:5-65 — routes cmd[0] to the matching repo
manager (case sensitive), renders the data-type help for unknown first
words, fans flush/converge to the repos, and joins shutdown.
"""

from __future__ import annotations

import asyncio
import hashlib
from contextlib import AsyncExitStack, asynccontextmanager

from .. import sessions as sessions_mod
from .help import DATATYPE_HELP, respond_help

SESSION_HELP = """\
The following are valid SESSION commands (docs/sessions.md):
  SESSION TOKEN                 - mint this node's session token
  SESSION WRAP <command...>     - apply a command, reply [reply, token]
  SESSION READ <token> <command...> - serve once the token is covered
                                  (bounded wait, then a STALE error),
                                  reply [token', reply]"""

# keyspace-range fanout for the anti-entropy digest tree (schema v8):
# every key lands in one of 256 stable buckets by the first byte of
# sha256(key) — a function of the KEY alone, so converged replicas
# bucket identically regardless of write order or backend. 256 leaves
# of 32 bytes each keep a whole-tree frame ~8 KB sparse-encoded, small
# enough to ship instead of a keyspace dump whenever root digests
# mismatch.
SYNC_FANOUT = 256


def sync_bucket(key: bytes) -> int:
    """The digest-tree leaf a key belongs to (stable across replicas)."""
    return hashlib.sha256(key).digest()[0]
from .manager import RepoManager
from .repo_bcount import RepoBCOUNT
from .repo_counters import RepoGCOUNT, RepoPNCOUNT
from .repo_map import RepoMAP
from .repo_system import RepoSYSTEM
from .repo_tensor import RepoTENSOR
from .repo_treg import RepoTREG
from .repo_tlog import RepoTLOG
from .repo_ujson import RepoUJSON

# THE data-type registry: every serving repo class, in the one fixed
# order every replica shares (it is the SyncRequest digest-vector order
# and the snapshot frame order). SYSTEM rides separately. Everything
# that enumerates types — DATA_TYPES, the digest trees, SYSTEM DIGEST
# TYPES, smoke3's per-type gate — derives from THIS tuple, so a new
# type cannot silently fall out of a digest-match gate. New entries
# append (the digest vector is positional across the wire).
DATA_REPO_CLASSES = (
    RepoTREG,
    RepoTLOG,
    RepoGCOUNT,
    RepoPNCOUNT,
    RepoUJSON,
    RepoTENSOR,
    RepoMAP,
    RepoBCOUNT,
)

DATA_TYPE_NAMES = tuple(cls.name for cls in DATA_REPO_CLASSES)


class Database:
    def __init__(
        self,
        identity: int,
        system_repo: RepoSYSTEM | None = None,
        engine: str = "auto",
    ):
        from ..native.engine import resolve_engine
        from ..obs.registry import MetricsRegistry

        # THIS instance's whole observability surface (obs/registry.py):
        # drain/journal/serving counters, latency histograms, gauges,
        # trace ring. Passed down explicitly to every component that
        # times or traces (repos, Server, Journal, Cluster) — the old
        # process-global dicts in utils/metrics.py cross-talked between
        # Databases in one process, which this retires.
        self.metrics = MetricsRegistry()
        # session guarantees (sessions.py): the node's applied-interval
        # vector + waiter queue, fed by the cluster engine and served by
        # the SESSION command family below. session_wait_ms is the
        # bounded-wait knob (--session-wait-ms); admission_cap the
        # per-command-class inflight cap (--admission-cap, 0 = off),
        # pushed onto every manager by set_admission_cap.
        self.sessions = sessions_mod.SessionIndex()
        self.session_wait_ms = sessions_mod.SESSION_WAIT_MS_DEFAULT
        self.system = system_repo if system_repo is not None else RepoSYSTEM(identity)
        # ONE native engine shared by every data repo AND the server's
        # batch applier (server/server.py): single source of host truth.
        # engine="python" pins the pure-Python table backends everywhere
        # (differential tests compare the two whole stacks).
        self.native_engine = resolve_engine(engine)
        self._map: dict[bytes, RepoManager] = {}
        # SYSTEM METRICS' "cmds" lines: THIS instance's Python-path
        # tally merged with THIS instance's engine counters — wired
        # per-Database (a global registry would cross-talk between
        # Database instances in tests/benches)
        self._served_py: dict[str, int] = {}
        self.system.served_fn = self._served_totals
        self.system.serving_fn = self.serving_totals
        for repo in tuple(
            cls(identity, engine=self.native_engine)
            for cls in DATA_REPO_CLASSES
        ) + (self.system,):
            # timed_drain resolves the registry through this attribute,
            # so drain counters/histograms land per-Database
            repo.metrics = self.metrics
            mgr = RepoManager(
                repo.name, repo, repo.help, served=self._served_py
            )
            mgr.registry = self.metrics  # admission BUSY refusal counts
            self._map[repo.name.encode()] = mgr

        # incremental sync digest (round-5 verdict item 2): per data type,
        # a map of key -> sha256(canonical per-key state) and the running
        # XOR of those hashes. Updating costs O(keys dirty since the last
        # pass) — a reconnect never dumps the keyspace to compute 32 bytes.
        # Derived from the registry, never hand-listed: a new repo class
        # lands in every digest surface automatically.
        self.DATA_TYPES = DATA_TYPE_NAMES
        self._sync_hash: dict[str, dict[bytes, bytes]] = {
            n: {} for n in self.DATA_TYPES
        }
        self._sync_xor: dict[str, bytes] = {
            n: bytes(32) for n in self.DATA_TYPES
        }
        # the keyspace-range digest tree (schema v8 Merkle-range repair):
        # per type, SYNC_FANOUT leaf accumulators — leaf b is the XOR of
        # the per-key hashes of every key whose sync_bucket is b, so the
        # XOR of all leaves IS _sync_xor and both update in the same
        # O(dirty) incremental fold. A sync responder whose root
        # mismatches ships these 256 x 32 bytes instead of the keyspace,
        # and the requester pulls only divergent buckets.
        self._sync_leaf: dict[str, list[int]] = {
            n: [0] * SYNC_FANOUT for n in self.DATA_TYPES
        }
        # bucket -> live keys, maintained by the same O(dirty) fold: the
        # range-serve path (dump_range_async) filters by membership here
        # instead of re-hashing every key in the keyspace per round — a
        # multi-round heal costs one sha256 per DIRTY key, not one per
        # key per round. References only (the keys already live in
        # _sync_hash), so the memory cost is pointer-sized.
        self._sync_bkeys: dict[str, list[set]] = {
            n: [set() for _ in range(SYNC_FANOUT)] for n in self.DATA_TYPES
        }
        # SYSTEM DIGEST (the drill matrix's convergence probe, exposed
        # to any Redis client): the async serving path computes it
        # under the repo locks (apply_async intercept below); the sync
        # single-threaded path goes through this hook on RepoSYSTEM
        self.system.digest_fn = self._sync_digest_blocking
        # SYSTEM DIGEST TYPES (the operator's divergence localizer):
        # per-type digest lines so an operator can name the diverged
        # TYPE before walking its ranges; same two-path wiring as the
        # combined digest
        self.system.digest_types_fn = self._sync_digest_types_blocking
        # SYSTEM METRICS' SESSION section (token/read/refusal counters)
        self.system.session_fn = self.sessions.metrics_totals
        # overload armor (admission.py): node-wide per-class admission,
        # consulted by the Server at every Python-path dispatch. The
        # default controller is unarmed (no policy, no byte bound) —
        # set_admission replaces it with the configured one and keeps
        # the OVERLOAD section of SYSTEM METRICS pointed at it.
        from ..admission import AdmissionController

        self.admission = AdmissionController(registry=self.metrics)
        self.system.overload_fn = self.admission.metrics_totals

    def _served_totals(self) -> dict[str, int]:
        """Commands served per type on BOTH paths (SYSTEM METRICS)."""
        totals = dict(self._served_py)
        if self.native_engine is not None:
            for name, n in self.native_engine.served_counts().items():
                if n:
                    totals[name] = totals.get(name, 0) + n
        return totals

    def serving_totals(self) -> dict[str, int]:
        """The native-vs-demoted serving split (SYSTEM METRICS SERVING
        lines, and the bench's recorded fallback_frac): commands the
        engine settled in C++ vs commands that went through the Python
        dispatch path (engine defers, demoted connections, and direct
        applies), plus whole-connection demotion events."""
        native = 0
        if self.native_engine is not None:
            native = sum(self.native_engine.served_counts().values())
        return {
            "native_cmds": native,
            "demoted_cmds": sum(self._served_py.values()),
            "demotions": self.metrics.serving_counters["demotions"],
            "busy_refusals": self.metrics.serving_counters["busy_refusals"],
        }

    def _sync_update_repo(self, name: str, repo) -> None:
        """Fold the repo's dirty keys into its digest accumulator (worker
        thread, repo lock held by the caller)."""
        prep = getattr(repo, "sync_prepare", None)
        if prep is not None:
            prep()
        dirty = repo.sync_dirty_keys()
        if not dirty:
            return
        hmap = self._sync_hash[name]
        leaves = self._sync_leaf[name]
        bkeys = self._sync_bkeys[name]
        x = int.from_bytes(self._sync_xor[name], "big")
        tag = name.encode()
        for key in dirty:
            bucket = sync_bucket(key)
            old = hmap.pop(key, None)
            if old is not None:
                o = int.from_bytes(old, "big")
                x ^= o
                leaves[bucket] ^= o
            canon = repo.sync_canon(key)
            if canon is not None:
                h = hashlib.sha256(
                    tag + b"\x00" + len(key).to_bytes(4, "big") + key + canon
                ).digest()
                hmap[key] = h
                hi = int.from_bytes(h, "big")
                x ^= hi
                leaves[bucket] ^= hi
                bkeys[bucket].add(key)
            else:
                bkeys[bucket].discard(key)
        self._sync_xor[name] = x.to_bytes(32, "big")

    async def sync_type_digests_async(self) -> tuple[bytes, ...]:
        """One 32-byte digest PER data type (DATA_TYPES order) — converged
        peers (any op order, any backend) produce equal bytes per type, so
        a sync responder streams only the types that actually differ.
        Cost is O(keys written since the last call): each repo folds only
        its dirty keys, under its own lock, in a worker thread."""
        for name in self.DATA_TYPES:
            mgr = self._map[name.encode()]
            async with mgr._lock:
                await asyncio.to_thread(self._sync_update_repo, name, mgr.repo)
        return tuple(self._sync_xor[n] for n in self.DATA_TYPES)

    async def sync_digest_async(self) -> bytes:
        """The combined 32-byte digest over every data type."""
        return hashlib.sha256(
            b"".join(await self.sync_type_digests_async())
        ).digest()

    async def sync_tree_async(self, name: str) -> tuple:
        """One type's keyspace-range digest tree as SPARSE leaves:
        ((bucket, 32-byte digest), ...) for the non-empty buckets only —
        the MsgDigestTree payload. Folds the type's dirty keys first
        (same O(dirty) incremental cost as the root digest)."""
        mgr = self._map[name.encode()]
        async with mgr._lock:
            await asyncio.to_thread(self._sync_update_repo, name, mgr.repo)
        return tuple(
            (i, v.to_bytes(32, "big"))
            for i, v in enumerate(self._sync_leaf[name])
            if v
        )

    async def dump_range_async(self, name: str, buckets) -> list:
        """One type's state RESTRICTED to the given digest-tree buckets,
        in the wire-delta shape: the MsgRangeRequest serve path. Dump +
        filter run in a worker thread under the repo lock, so a range
        serve stalls only its own type and only briefly — and the bytes
        it produces scale with the requested buckets, not the keyspace.
        Key selection goes through the bucket index (folded current
        first, O(dirty)), so a multi-round heal never re-hashes the
        keyspace per round."""
        mgr = self._map[name.encode()]

        def dump_filtered():
            self._sync_update_repo(name, mgr.repo)
            bkeys = self._sync_bkeys[name]
            wanted = set()
            for b in buckets:
                if 0 <= b < len(bkeys):
                    wanted |= bkeys[b]
            return [
                (key, delta)
                for key, delta in mgr.repo.dump_state()
                if key in wanted
            ]

        async with mgr._lock:
            return await asyncio.to_thread(dump_filtered)

    def _sync_digest_blocking(self) -> bytes:
        """The combined digest for SINGLE-THREADED callers (warmup,
        direct drives, tests): same bytes as sync_digest_async, no
        locks — the serving path never reaches this (apply_async
        intercepts SYSTEM DIGEST before repo dispatch)."""
        for name in self.DATA_TYPES:
            self._sync_update_repo(name, self._map[name.encode()].repo)
        return hashlib.sha256(
            b"".join(self._sync_xor[n] for n in self.DATA_TYPES)
        ).digest()

    def _sync_digest_types_blocking(self) -> list[tuple[str, bytes]]:
        """Per-type digests for SINGLE-THREADED callers — the sync-path
        SYSTEM DIGEST TYPES (the serving path intercepts in apply_async,
        which awaits the repo locks)."""
        for name in self.DATA_TYPES:
            self._sync_update_repo(name, self._map[name.encode()].repo)
        return [(n, self._sync_xor[n]) for n in self.DATA_TYPES]

    def set_admission(self, policy: str, queue_bytes: int) -> None:
        """Arm the node-wide overload armor (--admission-policy /
        --admission-queue-bytes, admission.py): per-class priority
        shedding under the declared OVERLOAD state plus the hard
        queued-bytes bound. Replaces the unarmed default controller."""
        from ..admission import AdmissionController

        self.admission = AdmissionController(
            policy, queue_bytes, registry=self.metrics
        )
        self.system.overload_fn = self.admission.metrics_totals

    def set_admission_cap(self, cap: int) -> None:
        """Per-command-class admission control (--admission-cap): each
        data-type manager refuses lock-queued commands past ``cap``
        in flight with a typed BUSY error, so one hot key's drain
        backlog degrades ITS command class, never the node. 0 = off."""
        for mgr in self._map.values():
            mgr.admission_cap = cap

    # ---- session guarantees (sessions.py, docs/sessions.md) ---------------

    async def _mint_token(self) -> bytes:
        """Force the pending local deltas through the cluster flush
        path (so every prior write on this connection is sequenced and
        the vector's own entry covers it), then encode the vector."""
        if self.sessions.flush_fn is not None:
            await self.sessions.flush_fn()
        self.sessions.stats["tokens_minted"] += 1
        return self.sessions.token_bytes()

    async def _apply_session(self, resp, cmd: list[bytes]) -> None:
        sess = self.sessions
        op = cmd[1] if len(cmd) > 1 else b""
        if op == b"TOKEN" and len(cmd) == 2:
            resp.string(await self._mint_token())
            return
        if op == b"WRAP" and len(cmd) > 2 and cmd[2] != b"SESSION":
            # the write reply carries the session token: one reply
            # array of [inner reply, token], the token minted AFTER the
            # inner command applied and flushed — read-your-writes
            # portable from this ack onward
            resp.array_start(2)
            await self.apply_async(resp, cmd[2:])
            resp.string(await self._mint_token())
            return
        if op == b"READ" and len(cmd) > 3 and cmd[3] != b"SESSION":
            try:
                token = sessions_mod.decode_token_memo(bytes(cmd[2]))
            except sessions_mod.SessionError as e:
                sess.stats["badtoken_refusals"] += 1
                resp.err(f"BADTOKEN (unusable session token: {e})")
                return
            if not await sess.wait_dominated(token, self.session_wait_ms):
                sess.stats["stale_refusals"] += 1
                resp.err(
                    "STALE (session token not covered within "
                    f"{self.session_wait_ms}ms; retry here later or "
                    "read where you wrote)"
                )
                return
            sess.stats["reads_served"] += 1
            # monotonic reads: the reply token is the join of what the
            # client presented and what this replica has verified — and
            # a SERVED read's vector dominates the token, so the join
            # IS the vector (memoised bytes, not a fresh encode)
            resp.array_start(2)
            resp.string(sess.token_bytes())
            await self.apply_async(resp, cmd[3:])
            return
        respond_help(resp, SESSION_HELP)

    def set_journal(self, journal) -> None:
        """Attach the delta write-ahead journal (journal/): every repo's
        flushed delta batches append to it before reaching the network
        sink (manager._emit). Pass None to detach. Attaching also arms
        the JOURNAL section of SYSTEM METRICS (explicit zeros from
        boot); the journal's own registry is whatever it was constructed
        with — main.py passes this Database's."""
        for mgr in self._map.values():
            mgr.journal = journal
        self.metrics.journal_enabled = journal is not None

    def manager(self, name: str) -> RepoManager:
        return self._map[name.encode()]

    def managers(self):
        return self._map.values()

    def apply(self, resp, cmd: list[bytes]) -> None:
        mgr = self._map.get(cmd[0]) if cmd else None
        if mgr is None:
            respond_help(resp, DATATYPE_HELP)
            return
        mgr.apply(resp, cmd)

    async def apply_async(self, resp, cmd: list[bytes]) -> None:
        """Serving path: per-repo locking + threaded drains (manager.py)."""
        if cmd and cmd[0] == b"SESSION":
            # session-guarantee surface (sessions.py): python-path only
            # — the native engine defers unknown first words, so a
            # session command rides the same per-repo async machinery
            # its inner command needs anyway
            await self._apply_session(resp, cmd)
            return
        if (
            len(cmd) == 3
            and cmd[0] == b"SYSTEM"
            and cmd[1] == b"DIGEST"
            and cmd[2] == b"TYPES"
        ):
            # the per-type breakdown of the digest below: one
            # "<TYPE> <hex>" line per data type, so an operator (or
            # scripts/smoke3.py's gate) can localize a divergence to a
            # type before walking its ranges
            digests = await self.sync_type_digests_async()
            resp.array_start(len(self.DATA_TYPES))
            for name, digest in zip(self.DATA_TYPES, digests):
                resp.string(f"{name} {digest.hex()}".encode())
            return
        if len(cmd) == 2 and cmd[0] == b"SYSTEM" and cmd[1] == b"DIGEST":
            # served here (not in RepoSYSTEM.apply, which is sync):
            # the digest takes every DATA repo's lock in turn, which
            # only the async path can await. The hex of the combined
            # per-type digest — equal bytes on converged replicas, so
            # "are these nodes (or lanes) converged?" is answerable
            # from any Redis client.
            digest = await self.sync_digest_async()
            resp.string(digest.hex().encode())
            return
        mgr = self._map.get(cmd[0]) if cmd else None
        if mgr is None:
            respond_help(resp, DATATYPE_HELP)
            return
        await mgr.apply_async(resp, cmd)

    async def converge_async(self, deltas) -> None:
        name, batch = deltas
        mgr = self._map.get(name.encode() if isinstance(name, str) else name)
        if mgr is not None:
            await mgr.converge_async(batch)

    async def flush_deltas_async(self, fn) -> None:
        for mgr in self._map.values():
            await mgr.flush_async(fn)

    def flush_deltas(self, fn) -> None:
        # jlint: order-ok — _map is built in the fixed constructor order,
        # identical on every replica; flush order is deterministic
        for mgr in self._map.values():
            mgr.flush_deltas(fn)

    def converge_deltas(self, deltas) -> None:
        """deltas: (type-name: str, [(key: bytes, delta), ...])."""
        name, batch = deltas
        mgr = self._map.get(name.encode() if isinstance(name, str) else name)
        if mgr is not None:
            mgr.converge_deltas(batch)

    def drain_all(self) -> None:
        for mgr in self._map.values():
            mgr.repo.drain()

    async def dump_state_async(self, names=None):
        """Full state per type for the cluster sync path: [(name, batch)].
        Each repo dumps under its own lock with device touches in a
        worker thread, so serving stalls only per-type and briefly —
        unlike the shutdown snapshot, no cross-repo atomicity is needed
        (the receiver's lattice join absorbs any in-between writes).
        ``names`` restricts the dump (the sync digest covers data types
        only; SYSTEM streams separately)."""
        out = []
        for mgr in self._map.values():
            if names is not None and mgr.name not in names:
                continue
            async with mgr._lock:
                batch = await asyncio.to_thread(mgr.repo.dump_state)
            out.append((mgr.name, batch))
        return out

    def clean_shutdown(self) -> None:
        """Single-threaded shutdown (tests / direct drivers); the serving
        stack uses clean_shutdown_async, which serialises with in-flight
        threaded drains."""
        for mgr in self._map.values():
            mgr.clean_shutdown()

    def stop_intake(self) -> None:
        """Reject new commands immediately (safe from a signal callback)."""
        for mgr in self._map.values():
            mgr._shutdown = True

    async def clean_shutdown_async(self) -> None:
        for mgr in self._map.values():
            await mgr.clean_shutdown_async()

    @asynccontextmanager
    async def all_locks(self):
        """Async context holding every repo lock (fixed order): the
        shutdown snapshot dumps under it so nothing mutates mid-dump."""
        async with AsyncExitStack() as stack:
            for mgr in self._map.values():
                await stack.enter_async_context(mgr._lock)
            yield


class _NullRespond:
    """Discards replies; lets warmup drive the real command paths."""

    def __getattr__(self, name):
        return lambda *a, **k: None


def warmup() -> None:
    """Pre-compile every serving-path device kernel at the default bucket
    shapes by driving a throwaway Database through one command of each
    kind. Without this, the FIRST client read after a write blocks the
    event loop for the XLA compile (seconds on a remote TPU) — long enough
    for peers to hit the 10-tick idle eviction and drop our connections,
    opening fire-and-forget delta-loss windows. jit caches are per-process,
    so the throwaway instance warms the real repos' kernels."""
    db = Database(identity=0)
    resp = _NullRespond()
    for line in (
        b"GCOUNT INC k 1",
        b"GCOUNT GET k",
        b"PNCOUNT INC k 1",
        b"PNCOUNT DEC k 1",
        b"PNCOUNT GET k",
        b"TREG SET k v 1",
        b"TREG GET k",
        b"TLOG INS k v 2",
        b"TLOG GET k",
        b"TLOG SIZE k",
        b"TLOG TRIM k 1",
        b"TLOG GET k",
        b"UJSON SET k a 1",
        b"UJSON GET k a",
        # the f32 payload (1.0f LE) is space-free, so the split survives
        b"TENSOR SET k MAX 1 \x00\x00\x80?",
        b"TENSOR GET k",
    ):
        db.apply(resp, line.split(b" "))
    # counter GETs after purely-local INCs serve from the host cache and
    # never touch the device; a foreign delta forces the drain kernels
    # (_drain_g/_drain_pn) through their XLA compile here, not mid-serving
    db.manager("GCOUNT").repo.converge(b"k", {7: 1})
    db.apply(resp, [b"GCOUNT", b"GET", b"k"])
    db.manager("PNCOUNT").repo.converge(b"k", ({7: 1}, {7: 1}))
    db.apply(resp, [b"PNCOUNT", b"GET", b"k"])
    # TENSOR GETs never touch the device; the threshold/converge drain
    # kernel compiles here at its default bucket shape, not mid-serving
    db.manager("TENSOR").repo.drain()
