"""TLOG repo: device-resident timestamped-log keyspace.

Reference analog: repo_tlog.pony:16-111 (Map[key -> TLog], per-key list
insertion). Here the keyspace is the padded ops/tlog plane block (narrow
2-plane layout until the first 64-bit timestamp widens it); local INS and
incoming delta logs buffer host-side per key and drain as ONE batched
merge dispatch at write thresholds and snapshots — TRIM/TRIMAT/CLR fuse
into that same dispatch (the kernel's per-row count column), and their
returned (length, cutoff) pairs maintain the host caches. Reads never drain: GET/SIZE/CUTOFF serve the exact merged
view (_merged_view — union + dedup + cutoff filter over the drained
render cache and the pending buffer, memoised per row); the only device
touch a read can make is the one-row gather that rebuilds the render
base after a drain or trim, and a quiescent read performs zero device
calls.

Delta wire shape: (entries: list[(value: bytes, ts: u64)], cutoff: u64).
"""

from __future__ import annotations

import jax
import numpy as np

from ..ops import hostref, tlog
from ..ops.interner import Interner
from ..parallel import (
    drain_sharded_tlog,
    route_drain64,
    serving_mesh,
    shard_plane,
    shard_vec,
)
from .base import PAD_ROW, ParseError, bucket, need, parse_opt_count, parse_u64
from ..utils.metrics import timed_drain
from .help import RepoHelp

# pending work flushes to the device at these sizes: reads never need a
# drain (the merged view computes host-side), so the thresholds bound
# host memory while keeping device batches large
ROW_DRAIN_THRESHOLD = 1024  # entries pending on one row
PENDING_DRAIN_THRESHOLD = 4096  # rows with pending work

# interner compaction: once the table holds this many more ids than live
# log entries, rebuild it from the live set (ops/interner.compact) so
# INS/TRIM churn can't grow host memory without bound
COMPACT_SLACK = 8192

TLOG_HELP = RepoHelp(
    "TLOG",
    {
        "GET": "key [count]",
        "INS": "key value timestamp",
        "SIZE": "key",
        "CUTOFF": "key",
        "TRIMAT": "key timestamp",
        "TRIM": "key count",
        "CLR": "key",
    },
)


@jax.jit
def _drain(state, ki, d_ts, d_vid, d_cut, counts):
    # fused merge + optional per-row trim (counts >= TRIM_NOOP are no-ops):
    # TRIM/CLR ride the same single dispatch as the drain they need first.
    # NOT donated: on overflow the caller retries from the pre-merge state
    st, ovf = tlog.converge_then_trim(state, ki, d_ts, d_vid, d_cut, ki, counts)
    return st, ovf, st.length[ki], st.cutoff[ki]


@jax.jit
def _drain_dense(state, d_ts, d_vid, d_cut, trim_ki, counts):
    # dense drain: delta rows aligned 1:1 with the keyspace — no gather or
    # scatter (ops/tlog converge_batch key_idx=None); full length/cutoff
    # vectors read back in the same launch
    st, ovf = tlog.converge_then_trim(
        state, None, d_ts, d_vid, d_cut, trim_ki, counts
    )
    return st, ovf, st.length, st.cutoff


@jax.jit
def _get_row(state, k):
    ts, vid, _length = tlog.read_row(state, k)
    return ts, vid


class RepoTLOG:
    name = "TLOG"
    help = TLOG_HELP

    def __init__(
        self, identity: int, key_cap: int = 1024, len_cap: int = 16, mesh="auto"
    ):
        # identity unused: log entries carry no replica identity
        self._keys: dict[bytes, int] = {}
        # mesh mode mirrors the counter/TREG repos: with >1 visible device
        # the segment tensors live keys-sharded and drains/trims route
        # through parallel/sharded
        self._mesh = serving_mesh() if mesh == "auto" else mesh
        self._n_shards = self._mesh.devices.size if self._mesh is not None else 1
        self._key_cap = self._round_cap(key_cap)
        self._len_cap = len_cap
        # mesh mode always uses the wide (3-plane) layout: the shard_map
        # drains have one fixed plane structure; single-chip serving keeps
        # the narrow 2-plane layout until a 64-bit timestamp arrives
        self._state = self._place(
            tlog.init(self._key_cap, len_cap, wide=self._mesh is not None)
        )
        self._interner = Interner()
        self._len_cache: dict[int, int] = {}  # row -> length
        self._cut_cache: dict[int, int] = {}  # row -> cutoff
        # row -> desc-sorted [(ts, value)], the rendered GET view; built on
        # first read, dropped whenever a drain or trim touches the row — so
        # quiescent GETs never dispatch to the device (the counter repos'
        # host-shadow pattern, repo_counters.py)
        self._render: dict[int, list[tuple[int, bytes]]] = {}
        # row -> [(pend_len, cutoff), merged SET, sorted list|None]: the
        # read-time merge memo; local inserts extend the set in place
        # (_note_local_insert), SIZE reads len(set), GET materialises the
        # (ts, value)-desc list lazily
        self._merged: dict[int, list] = {}
        # row -> (entries [(ts, value)], incoming-delta cutoff)
        self._pend_entries: dict[int, list[tuple[int, bytes]]] = {}
        self._pend_cutoff: dict[int, int] = {}
        self._row_overdue = False  # some row crossed ROW_DRAIN_THRESHOLD
        self._deltas: dict[bytes, hostref.TLog] = {}

    def _round_cap(self, k: int) -> int:
        """Key capacity must split evenly over the mesh's keys axis."""
        ns = self._n_shards
        return -(-k // ns) * ns

    def _place(self, state):
        """(Re-)place state tensors keys-sharded when a mesh is active."""
        if self._mesh is None:
            return state
        return tlog.TLogState(
            shard_plane(self._mesh, state.nth),
            shard_plane(self._mesh, state.ntl),
            shard_plane(self._mesh, state.nv),
            shard_vec(self._mesh, state.length),
            shard_vec(self._mesh, state.cutoff),
        )

    def _row_for(self, key: bytes) -> int:
        row = self._keys.get(key)
        if row is None:
            row = len(self._keys)
            self._keys[key] = row
        return row

    def _delta_for(self, key: bytes) -> hostref.TLog:
        d = self._deltas.get(key)
        if d is None:
            d = self._deltas[key] = hostref.TLog()
        return d

    # -- commands (repo_tlog.pony:29-111) ----------------------------------

    def apply(self, resp, args: list[bytes]) -> bool:
        op = need(args, 0)
        if op == b"GET":
            self._cmd_get(resp, need(args, 1), parse_opt_count(args, 2))
            return False
        if op == b"INS":
            key = need(args, 1)
            value = need(args, 2)
            ts = parse_u64(need(args, 3))
            row = self._row_for(key)
            lst = self._pend_entries.setdefault(row, [])
            lst.append((ts, value))
            self._note_local_insert(row, ts, value)
            if ts >= self._cut_cache.get(row, 0):
                self._delta_for(key).insert(value, ts)
            if (
                len(lst) >= ROW_DRAIN_THRESHOLD
                or len(self._pend_entries) >= PENDING_DRAIN_THRESHOLD
            ):
                self.drain()
            resp.ok()
            return True
        if op == b"SIZE":
            row = self._keys.get(need(args, 1))
            if row is None:
                resp.u64(0)
            elif self._quiescent(row):
                resp.u64(self._len_cache.get(row, 0))  # O(1), no gather
            else:
                resp.u64(len(self._merged_set(row)))  # O(1) on cache hit
            return False
        if op == b"CUTOFF":
            row = self._keys.get(need(args, 1))
            resp.u64(self._cutoff_view(row) if row is not None else 0)
            return False
        if op == b"TRIMAT":
            key = need(args, 1)
            ts = parse_u64(need(args, 2))
            self._device_trimat(key, ts)
            resp.ok()
            return True
        if op == b"TRIM":
            key = need(args, 1)
            count = parse_u64(need(args, 2))
            self._device_trim(key, count)
            resp.ok()
            return True
        if op == b"CLR":
            self._device_trim(need(args, 1), 0)
            resp.ok()
            return True
        raise ParseError()

    def _drained_entries(self, row: int) -> list[tuple[int, bytes]]:
        """The drained part of a row, (ts, value) desc — the render cache,
        rebuilt from ONE device row gather when a drain/trim dropped it."""
        ents = self._render.get(row)
        if ents is None:
            length = self._len_cache.get(row, 0)
            if length == 0:
                ents = []
            else:
                ts_row, vid_row = _get_row(self._state, row)
                ts_row = np.asarray(ts_row)
                vid_row = np.asarray(vid_row)
                ents = [
                    (int(ts_row[i]), self._interner.lookup(int(vid_row[i])))
                    for i in range(length)
                ]
                ents.sort(reverse=True)
            self._render[row] = ents
        return ents

    def _cutoff_view(self, row: int) -> int:
        return max(self._cut_cache.get(row, 0), self._pend_cutoff.get(row, 0))

    def _quiescent(self, row: int) -> bool:
        return row not in self._pend_entries and self._cutoff_view(
            row
        ) == self._cut_cache.get(row, 0)

    def _merged_set(self, row: int) -> set:
        """The merged log as a SET — drained ∪ pending, deduped (equal ts
        AND value), cutoff-filtered. The cache entry is a mutable
        ``[state, set, sorted_list|None]``: local inserts extend the set
        incrementally (the INS hot path), SIZE reads its len in O(1), and
        the (ts, value)-desc list materialises lazily only when a GET
        actually needs order. The lattice merge is a set union, so the
        host and device merges agree exactly (tlog.md:116-133)."""
        cut = self._cutoff_view(row)
        state = (len(self._pend_entries.get(row, ())), cut)
        hit = self._merged.get(row)
        if hit is not None and hit[0] == state:
            return hit[1]
        base = self._drained_entries(row)
        pend = self._pend_entries.get(row)
        merged = {e for e in base if e[0] >= cut}
        merged.update(e for e in pend or () if e[0] >= cut)
        self._merged[row] = [state, merged, None]
        return merged

    def _merged_view(self, row: int) -> tuple[list[tuple[int, bytes]], int]:
        """The exact log as a drain would leave it, (ts, value) desc —
        computed on the host: reads NEVER pay a device drain (at most one
        row gather for the drained base)."""
        cut = self._cutoff_view(row)
        if self._quiescent(row):
            return self._drained_entries(row), cut
        self._merged_set(row)
        hit = self._merged[row]
        if hit[2] is None:
            hit[2] = sorted(hit[1], reverse=True)
        return hit[2], cut

    def _note_local_insert(self, row: int, ts: int, value: bytes) -> None:
        """Keep the merged cache exact across a local INS without a
        rebuild: the entry joins the set (dedup by membership) and the
        sorted list invalidates lazily. Anything else (stale state)
        drops the cache."""
        hit = self._merged.get(row)
        if hit is None:
            return
        cut = self._cutoff_view(row)
        if hit[0] != (len(self._pend_entries[row]) - 1, cut):
            self._merged.pop(row, None)
            return
        if ts >= cut:
            e = (ts, value)
            if e not in hit[1]:
                hit[1].add(e)
                hit[2] = None  # order dirty; rebuilt on next GET
        hit[0] = (len(self._pend_entries[row]), cut)

    def _cmd_get(self, resp, key: bytes, count: int) -> None:
        row = self._keys.get(key)
        if row is None:
            resp.array_start(0)
            return
        ents, _cut = self._merged_view(row)
        n = min(count, len(ents))
        resp.array_start(n)
        for ts, value in ents[:n]:
            resp.array_start(2)
            resp.string(value)
            resp.u64(ts)

    def _device_trimat(self, key: bytes, ts: int) -> None:
        """TRIMAT == TRIM with a direct cutoff target: raise the pending
        cutoff and drain ONCE — the merge joins pending entries and the new
        cutoff in the same lattice op ((S ⊔ P) ⊔ C == S ⊔ (P ⊔ C)), so the
        old drain-set-drain double dispatch was pure overhead (VERDICT r2
        weak item 6)."""
        row = self._row_for(key)
        self._pend_cutoff[row] = max(self._pend_cutoff.get(row, 0), ts)
        self.drain()
        self._delta_for(key).raise_cutoff(self._cut_cache.get(row, 0))

    def _device_trim(self, key: bytes, count: int) -> None:
        """TRIM/CLR: the trim needs the row's pending entries merged
        first, so it rides the drain dispatch as the fused per-row count
        column — ONE launch total (was drain-then-trim, two)."""
        row = self._row_for(key)
        # counts above any possible length are no-ops (tlog.md:58); clamping
        # to the kernel sentinel keeps huge client counts out of int64 range
        self.drain(trim=(row, min(count, tlog.TRIM_NOOP)))
        self._delta_for(key).raise_cutoff(self._cut_cache[row])

    # -- lattice plumbing ---------------------------------------------------

    def converge(self, key: bytes, delta: tuple) -> None:
        # buffer only: the serving path drains via drain_overdue in a
        # worker thread; sync callers (snapshot restore) drain explicitly
        entries, cutoff = delta
        row = self._row_for(key)
        if entries:
            lst = self._pend_entries.setdefault(row, [])
            lst.extend((ts, value) for value, ts in entries)
            if len(lst) >= ROW_DRAIN_THRESHOLD:
                self._row_overdue = True
        if cutoff:
            self._pend_cutoff[row] = max(self._pend_cutoff.get(row, 0), cutoff)

    def deltas_size(self) -> int:
        return len(self._deltas)

    def may_drain(self, args: list[bytes]) -> bool:
        """Device-bound commands the server offloads to a thread: trims
        always dispatch; an INS that will tip a drain threshold does.
        Reads NEVER drain — GET/SIZE/CUTOFF serve the exact merged view
        host-side (_merged_view) — but the first read after a drain/trim
        rebuilds the render base with one device row gather, and over a
        tunneled chip one dispatch can cost ~100 ms: offload it too so it
        never stalls the event loop (the counter repos' foreign-GET
        pattern)."""
        if not args:
            return False
        op = args[0]
        if op in (b"TRIM", b"TRIMAT", b"CLR"):
            return True
        if op == b"INS" and len(args) >= 2:
            row = self._keys.get(args[1])
            in_row = len(self._pend_entries.get(row, ())) if row is not None else 0
            return (
                in_row + 1 >= ROW_DRAIN_THRESHOLD
                or len(self._pend_entries) + 1 >= PENDING_DRAIN_THRESHOLD
            )
        if op in (b"GET", b"SIZE") and len(args) >= 2:
            row = self._keys.get(args[1])
            if row is None:
                return False
            if op == b"SIZE" and self._quiescent(row):
                return False  # O(1) length-cache answer, no gather
            return row not in self._render and self._len_cache.get(row, 0) > 0
        return False

    def drain_overdue(self) -> bool:
        """Cluster converge path: after buffering a batch, the manager
        offloads the drain to a worker thread when any threshold trips.
        O(1): converge flags row-threshold crossings as it appends."""
        return (
            self._row_overdue
            or len(self._pend_entries) >= PENDING_DRAIN_THRESHOLD
        )

    def flush_deltas(self):
        out = [
            (k, (d.latest(), d.cutoff)) for k, d in sorted(self._deltas.items())
        ]
        self._deltas.clear()
        return out

    # -- snapshot (persist.py): full state in the wire-delta shape ----------

    def dump_state(self):
        self.drain()
        # one bulk device->host pull, then slice rows locally (a per-key
        # jitted gather would be O(keys) dispatches inside shutdown)
        st = self._state
        all_ts = tlog.decode_ts_np(
            None if st.nth is None else np.asarray(st.nth), np.asarray(st.ntl)
        )
        all_vid = tlog.decode_vid_np(np.asarray(st.nv))
        out = []
        for key, row in sorted(self._keys.items()):
            length = self._len_cache.get(row, 0)
            cutoff = self._cut_cache.get(row, 0)
            entries = [
                (self._interner.lookup(int(all_vid[row, i])), int(all_ts[row, i]))
                for i in range(length)
            ]
            if entries or cutoff:
                out.append((key, (entries, cutoff)))
        return out

    def load_state(self, batch) -> None:
        for key, delta in batch:
            self.converge(key, delta)

    def _maybe_compact_interner(self) -> None:
        """Epoch compaction (weak-spot fix, VERDICT round 2): every value
        ever INSerted kept its interner slot after being trimmed away.
        Live ids are exactly the device rows' first `length` slots
        (canonical order scrubs the rest to -1), so pull the vid plane
        once, rebuild the table from the live set, and push the remapped
        plane back. Runs under the repo lock at drain time, before any
        new pending values intern."""
        live = sum(self._len_cache.values())
        if len(self._interner) <= 2 * live + COMPACT_SLACK:
            return
        all_vid = tlog.decode_vid_np(np.asarray(self._state.nv))  # one pull
        rows = [
            all_vid[row, :length]
            for row, length in self._len_cache.items()
            if length > 0
        ]
        flat = np.concatenate(rows) if rows else np.empty(0, np.int64)
        remap = self._interner.compact(flat[flat >= 0])
        new_vid = np.full(all_vid.shape, -1, np.int64)
        for row, length in self._len_cache.items():
            if length > 0:
                src = all_vid[row, :length]
                # mask negatives on application exactly as on collection:
                # remap[-1] would silently alias the last live id
                new_vid[row, :length] = np.where(
                    src >= 0, remap[np.clip(src, 0, None)], -1
                )
        new_nv = tlog.encode_vid_np(new_vid)
        self._state = self._state._replace(
            nv=shard_plane(self._mesh, new_nv)
            if self._mesh is not None
            else jax.numpy.asarray(new_nv)
        )

    def _finish_drain(self, updates) -> None:
        """Common drain epilogue: refresh the per-row host caches from the
        kernel's (row, length, cutoff) read-backs, then clear pending."""
        for row, ln, ct in updates:
            self._render.pop(row, None)
            self._merged.pop(row, None)
            self._len_cache[row] = int(ln)
            self._cut_cache[row] = int(ct)
        self._pend_entries.clear()
        self._pend_cutoff.clear()
        self._row_overdue = False

    @timed_drain(
        "TLOG",
        lambda self: len(set(self._pend_entries) | set(self._pend_cutoff)),
    )
    def drain(self, trim: tuple[int, int] | None = None) -> None:
        """Flush pending entries/cutoffs in one dispatch; with ``trim``
        = (row, count), the TRIM/CLR of that row fuses into the SAME
        dispatch via the kernel's per-row count column (counts of
        TRIM_NOOP leave other rows untouched)."""
        if not self._pend_entries and not self._pend_cutoff and trim is None:
            return
        self._maybe_compact_interner()
        # adaptive layout: the narrow (2-plane) state holds every ts below
        # TS32_MAX; the first wider timestamp or cutoff upgrades it
        # losslessly before this drain ships (mesh states start wide)
        if not self._state.wide and (
            any(
                ts > tlog.TS32_MAX
                for lst in self._pend_entries.values()
                for ts, _ in lst
            )
            or any(c > tlog.TS32_MAX for c in self._pend_cutoff.values())
        ):
            self._state = tlog.widen(self._state)
        row_set = set(self._pend_entries) | set(self._pend_cutoff)
        if trim is not None:
            row_set.add(trim[0])
        rows = sorted(row_set)
        # capacity: keys, then entry slots (worst case current + pending)
        kcap = self._round_cap(bucket(max(len(self._keys), 1), self._key_cap))
        need_len = max(
            self._len_cache.get(r, 0) + len(self._pend_entries.get(r, ()))
            for r in rows
        )
        lcap = bucket(max(need_len, 1), self._len_cap)
        if kcap != self._key_cap or lcap != self._len_cap:
            self._key_cap, self._len_cap = kcap, lcap
            self._state = self._place(tlog.grow(self._state, kcap, lcap))
        if self._mesh is not None:
            self._drain_sharded(rows, trim)
            return
        while True:
            ld = bucket(
                max((len(self._pend_entries.get(r, ())) for r in rows), default=1),
                1,
            )
            # dense path (repo_counters precedent): when the batch covers a
            # quarter of the keyspace and rows are narrow, aligned delta
            # rows skip the gather/scatter entirely
            dense = len(rows) * 4 >= self._key_cap and ld <= 64
            if dense:
                kc = self._key_cap
                d_ts = np.zeros((kc, ld), np.uint64)
                d_vid = np.full((kc, ld), -1, np.int64)
                d_cut = np.zeros(kc, np.uint64)
                for row in rows:
                    for j, (ts, value) in enumerate(
                        self._pend_entries.get(row, ())
                    ):
                        d_ts[row, j] = ts
                        d_vid[row, j] = self._interner.intern(value)
                    d_cut[row] = self._pend_cutoff.get(row, 0)
                tb = bucket(1)
                trim_ki = np.full(tb, PAD_ROW, np.int32)
                counts = np.full(tb, tlog.TRIM_NOOP, np.int64)
                if trim is not None:
                    trim_ki[0], counts[0] = trim
                new_state, ovf, lens, cuts = _drain_dense(
                    self._state, d_ts, d_vid, d_cut, trim_ki, counts
                )
                # check EVERY row: the dense kernel flags any row whose
                # entries reach into the tail columns the delta writes
                # through, including rows with no pending delta
                if bool(np.asarray(ovf).any()):
                    self._len_cap *= 2
                    self._state = tlog.grow(
                        self._state, self._key_cap, self._len_cap
                    )
                    continue
                self._state = new_state
                lens = np.asarray(lens)
                cuts = np.asarray(cuts)
                self._finish_drain((r, lens[r], cuts[r]) for r in rows)
                return
            b = bucket(len(rows))
            ki = np.full(b, PAD_ROW, np.int32)
            d_ts = np.zeros((b, ld), np.uint64)
            d_vid = np.full((b, ld), -1, np.int64)
            d_cut = np.zeros(b, np.uint64)
            counts = np.full(b, tlog.TRIM_NOOP, np.int64)
            for i, row in enumerate(rows):
                ki[i] = row
                for j, (ts, value) in enumerate(self._pend_entries.get(row, ())):
                    d_ts[i, j] = ts
                    d_vid[i, j] = self._interner.intern(value)
                d_cut[i] = self._pend_cutoff.get(row, 0)
                if trim is not None and row == trim[0]:
                    counts[i] = trim[1]
            new_state, ovf, lens, cuts = _drain(
                self._state, ki, d_ts, d_vid, d_cut, counts
            )
            if bool(np.asarray(ovf)[: len(rows)].any()):
                # retry from the retained pre-merge state with doubled slots
                self._len_cap *= 2
                self._state = tlog.grow(self._state, self._key_cap, self._len_cap)
                continue
            self._state = new_state
            lens = np.asarray(lens)
            cuts = np.asarray(cuts)
            self._finish_drain(zip(rows, lens, cuts))
            return

    def _drain_sharded(self, rows, trim=None) -> None:
        """Mesh-mode drain: per-row deltas route as u64 payload columns
        [ts(ld) | vid(ld) | cutoff | count]; the batched merge + fused
        trim runs per key block with per-slot lengths/cutoffs read back in
        the same launch. Same overflow-retry contract as the single-chip
        path."""
        import jax.numpy as jnp

        while True:
            ld = bucket(
                max((len(self._pend_entries.get(r, ())) for r in rows), default=1),
                1,
            )
            payload = np.zeros((len(rows), 2 * ld + 2), np.uint64)
            # empty vid slots must read back as -1, not id 0
            payload[:, ld : 2 * ld] = np.uint64(0xFFFFFFFFFFFFFFFF)
            payload[:, 2 * ld + 1] = np.uint64(tlog.TRIM_NOOP)
            for i, row in enumerate(rows):
                for j, (ts, value) in enumerate(self._pend_entries.get(row, ())):
                    payload[i, j] = ts
                    payload[i, ld + j] = self._interner.intern(value)
                payload[i, 2 * ld] = self._pend_cutoff.get(row, 0)
                if trim is not None and row == trim[0]:
                    payload[i, 2 * ld + 1] = trim[1]
            lr, pay, slots = route_drain64(
                np.asarray(rows, np.int64),
                payload,
                self._n_shards,
                self._key_cap // self._n_shards,
            )
            out = drain_sharded_tlog(
                self._mesh, *self._state, lr, jnp.asarray(pay), ld
            )
            ovf = np.asarray(out[5])
            if bool(ovf[slots >= 0].any()):
                # retry from the retained pre-merge state with doubled slots
                self._len_cap *= 2
                self._state = self._place(
                    tlog.grow(self._state, self._key_cap, self._len_cap)
                )
                continue
            self._state = tlog.TLogState(*out[:5])
            lens, cuts = np.asarray(out[6]), np.asarray(out[7])
            self._finish_drain(
                (int(g), lens[j], cuts[j])
                for j, g in enumerate(slots)
                if g >= 0
            )
            return
