"""TLOG repo: device-resident timestamped-log keyspace.

Reference analog: repo_tlog.pony:16-111 (Map[key -> TLog], per-key list
insertion). Here the keyspace is the padded ops/tlog plane block (narrow
2-plane layout until the first 64-bit timestamp widens it); local INS and
incoming delta logs buffer host-side per key and drain as ONE batched
merge dispatch at write thresholds and snapshots — TRIM/TRIMAT/CLR fuse
into that same dispatch (the kernel's per-row count column), and their
returned (length, cutoff) pairs maintain the host caches. Reads never
drain: GET/SIZE/CUTOFF serve the exact merged view (union + dedup +
cutoff filter over the drained base and the pending buffer, memoised per
row); the only device touch a read can make is the one-row gather that
rebuilds the render base after a drain whose merged view was not
current, and a quiescent read performs zero device calls.

Host bookkeeping (keys, pending windows, length/cutoff caches, the
merged-view memo, delta accumulators) lives behind the table backends in
tlog_table.py: pure-Python as the oracle, or the native C++ engine — the
SAME state the server's native batch applier (native/serve_engine.cpp)
mutates, so INS/SIZE/GET/CUTOFF settled natively and Python-side
drains/flushes share one source of truth.

Delta wire shape: (entries: list[(value: bytes, ts: u64)], cutoff: u64).
"""

from __future__ import annotations

import jax
import numpy as np

from ..native.engine import resolve_engine
from ..ops import tlog
from ..ops.interner import Interner
from ..parallel import (
    drain_sharded_tlog,
    route_drain64,
    serving_mesh,
    shard_plane,
    shard_vec,
)
from .base import PAD_ROW, ParseError, bucket, need, parse_opt_count, parse_u64
from .tlog_table import (
    NativeTlogTable,
    PENDING_DRAIN_THRESHOLD,
    PyTlogTable,
    ROW_DRAIN_THRESHOLD,
)
from ..utils.metrics import timed_drain
from .help import RepoHelp

# interner compaction: once the table holds this many more ids than live
# log entries, rebuild it from the live set (ops/interner.compact) so
# INS/TRIM churn can't grow host memory without bound
COMPACT_SLACK = 8192

TLOG_HELP = RepoHelp(
    "TLOG",
    {
        "GET": "key [count]",
        "INS": "key value timestamp",
        "SIZE": "key",
        "CUTOFF": "key",
        "TRIMAT": "key timestamp",
        "TRIM": "key count",
        "CLR": "key",
    },
)


@jax.jit
def _drain(state, ki, d_ts, d_vid, d_cut, counts):
    # fused merge + optional per-row trim (counts >= TRIM_NOOP are no-ops):
    # TRIM/CLR ride the same single dispatch as the drain they need first.
    # NOT donated: on overflow the caller retries from the pre-merge state
    st, ovf = tlog.converge_then_trim(state, ki, d_ts, d_vid, d_cut, ki, counts)
    return st, ovf, st.length[ki], st.cutoff[ki]


@jax.jit
def _drain_dense(state, d_ts, d_vid, d_cut, trim_ki, counts):
    # dense drain: delta rows aligned 1:1 with the keyspace — no gather or
    # scatter (ops/tlog converge_batch key_idx=None); full length/cutoff
    # vectors read back in the same launch
    st, ovf = tlog.converge_then_trim(
        state, None, d_ts, d_vid, d_cut, trim_ki, counts
    )
    return st, ovf, st.length, st.cutoff


@jax.jit
def _get_row(state, k):
    ts, vid, _length = tlog.read_row(state, k)
    return ts, vid


class RepoTLOG:
    name = "TLOG"
    help = TLOG_HELP

    def __init__(
        self,
        identity: int,
        key_cap: int = 1024,
        len_cap: int = 16,
        mesh="auto",
        engine="auto",
    ):
        # identity unused: log entries carry no replica identity
        # mesh mode mirrors the counter/TREG repos: with >1 visible device
        # the segment tensors live keys-sharded and drains/trims route
        # through parallel/sharded
        self._mesh = serving_mesh() if mesh == "auto" else mesh
        self._n_shards = self._mesh.devices.size if self._mesh is not None else 1
        self._key_cap = self._round_cap(key_cap)
        self._len_cap = len_cap
        # mesh mode always uses the wide (3-plane) layout: the shard_map
        # drains have one fixed plane structure; single-chip serving keeps
        # the narrow 2-plane layout until a 64-bit timestamp arrives
        self._state = self._place(
            tlog.init(self._key_cap, len_cap, wide=self._mesh is not None)
        )
        self._interner = Interner()
        self.engine = engine = resolve_engine(engine)
        self._tbl = (
            NativeTlogTable(engine) if engine is not None else PyTlogTable()
        )
        # row -> desc-sorted [(ts, value)], the rendered drained part; built
        # on first read, dropped whenever a drain or trim touches the row —
        # so quiescent GETs never dispatch to the device
        self._render: dict[int, list[tuple[int, bytes]]] = {}
        # row -> (table gen, desc-sorted merged list): the GET-order memo
        # over the table's merged view
        self._sorted: dict[int, tuple[int, list[tuple[int, bytes]]]] = {}

    def _round_cap(self, k: int) -> int:
        """Key capacity must split evenly over the mesh's keys axis."""
        ns = self._n_shards
        return -(-k // ns) * ns

    def _place(self, state):
        """(Re-)place state tensors keys-sharded when a mesh is active."""
        if self._mesh is None:
            return state
        return tlog.TLogState(
            shard_plane(self._mesh, state.nth),
            shard_plane(self._mesh, state.ntl),
            shard_plane(self._mesh, state.nv),
            shard_vec(self._mesh, state.length),
            shard_vec(self._mesh, state.cutoff),
        )

    # -- commands (repo_tlog.pony:29-111) ----------------------------------

    def apply(self, resp, args: list[bytes]) -> bool:
        op = need(args, 0)
        if op == b"GET":
            self._cmd_get(resp, need(args, 1), parse_opt_count(args, 2))
            return False
        if op == b"INS":
            key = need(args, 1)
            value = need(args, 2)
            ts = parse_u64(need(args, 3))
            row = self._tbl.upsert(key)
            self._tbl.ins(row, ts, value)
            if (
                self._tbl.pend_len(row) >= ROW_DRAIN_THRESHOLD
                or self._tbl.pend_rows_count() >= PENDING_DRAIN_THRESHOLD
            ):
                self.drain()
            resp.ok()
            return True
        if op == b"SIZE":
            row = self._tbl.find(need(args, 1))
            if row < 0:
                resp.u64(0)
            elif self._tbl.quiescent(row):
                resp.u64(self._tbl.len_cache(row))  # O(1), no gather
            else:
                resp.u64(self._size_nonquiescent(row))
            return False
        if op == b"CUTOFF":
            row = self._tbl.find(need(args, 1))
            resp.u64(self._tbl.cutoff_view(row) if row >= 0 else 0)
            return False
        if op == b"TRIMAT":
            key = need(args, 1)
            ts = parse_u64(need(args, 2))
            self._device_trimat(key, ts)
            resp.ok()
            return True
        if op == b"TRIM":
            key = need(args, 1)
            count = parse_u64(need(args, 2))
            self._device_trim(key, count)
            resp.ok()
            return True
        if op == b"CLR":
            self._device_trim(need(args, 1), 0)
            resp.ok()
            return True
        raise ParseError()

    def _drained_entries(self, row: int) -> list[tuple[int, bytes]]:
        """The drained part of a row, (ts, value) desc — the render cache.
        A miss serves from the table's carried base when it is valid (the
        common case: the drain kept the exact row content host-side); only
        a base-invalid row pays the ONE device row gather — and then
        REPAIRS the table's base from it (ADVICE round 5): without the
        repair a quiescent row whose drain landed while the merged memo
        was stale would serve correctly but never settle natively again,
        paying the FFI stop + Python dispatch on every later GET."""
        ents = self._render.get(row)
        if ents is None:
            length = self._tbl.len_cache(row)
            if length == 0:
                ents = []
            else:
                base = self._tbl.base_entries(row)
                if base is not None:
                    ents = sorted(base, reverse=True)
                else:
                    ts_row, vid_row = _get_row(self._state, row)
                    ts_row = np.asarray(ts_row)
                    vid_row = np.asarray(vid_row)
                    ents = [
                        (int(ts_row[i]), self._interner.lookup(int(vid_row[i])))
                        for i in range(length)
                    ]
                    ents.sort(reverse=True)
            self._render[row] = ents
        if not self._tbl.base_valid(row):
            self._tbl.set_base(row, ents)
        return ents

    def _size_nonquiescent(self, row: int) -> int:
        """Merged-view size with the drained-base handshake: the table
        serves it host-side unless its base is unknown (a drain landed
        while the merged memo was stale), in which case ONE device row
        gather rebuilds it (_drained_entries also writes it back as the
        table's base)."""
        n = self._tbl.size(row)
        if n < 0:
            self._drained_entries(row)
            n = self._tbl.size(row)
        return n

    def _merged_view(self, row: int) -> tuple[list[tuple[int, bytes]], int]:
        """The exact log as a drain would leave it, (ts, value) desc —
        computed on the host: reads NEVER pay a device drain (at most one
        row gather for the drained base)."""
        cut = self._tbl.cutoff_view(row)
        if self._tbl.quiescent(row):
            return self._drained_entries(row), cut
        self._size_nonquiescent(row)  # ensure the merged memo is current
        gen = self._tbl.gen(row)
        hit = self._sorted.get(row)
        if hit is not None and hit[0] == gen:
            return hit[1], cut
        ents = sorted(self._tbl.merged_entries(row), reverse=True)
        self._sorted[row] = (gen, ents)
        return ents, cut

    def _cmd_get(self, resp, key: bytes, count: int) -> None:
        row = self._tbl.find(key)
        if row < 0:
            resp.array_start(0)
            return
        ents, _cut = self._merged_view(row)
        n = min(count, len(ents))
        resp.array_start(n)
        for ts, value in ents[:n]:
            resp.array_start(2)
            resp.string(value)
            resp.u64(ts)

    def _device_trimat(self, key: bytes, ts: int) -> None:
        """TRIMAT == TRIM with a direct cutoff target: raise the pending
        cutoff and drain ONCE — the merge joins pending entries and the new
        cutoff in the same lattice op ((S ⊔ P) ⊔ C == S ⊔ (P ⊔ C)), so the
        old drain-set-drain double dispatch was pure overhead (VERDICT r2
        weak item 6)."""
        row = self._tbl.upsert(key)
        self._tbl.converge_cutoff(row, ts)
        self.drain()
        self._tbl.delta_raise_cutoff(row, self._tbl.cut_cache(row))

    def _device_trim(self, key: bytes, count: int) -> None:
        """TRIM/CLR: the trim needs the row's pending entries merged
        first, so it rides the drain dispatch as the fused per-row count
        column — ONE launch total (was drain-then-trim, two)."""
        row = self._tbl.upsert(key)
        # counts above any possible length are no-ops (tlog.md:58); clamping
        # to the kernel sentinel keeps huge client counts out of int64 range
        self.drain(trim=(row, min(count, tlog.TRIM_NOOP)))
        self._tbl.delta_raise_cutoff(row, self._tbl.cut_cache(row))

    # -- lattice plumbing ---------------------------------------------------

    def converge(self, key: bytes, delta: tuple) -> None:
        # buffer only: the serving path drains via drain_overdue in a
        # worker thread; sync callers (snapshot restore) drain explicitly
        entries, cutoff = delta
        row = self._tbl.upsert(key)
        for value, ts in entries:
            self._tbl.converge_entry(row, ts, value)
        if cutoff:
            self._tbl.converge_cutoff(row, cutoff)

    def deltas_size(self) -> int:
        return self._tbl.deltas_size()

    def may_drain(self, args: list[bytes]) -> bool:
        """Device-bound commands the server offloads to a thread: trims
        always dispatch; an INS that will tip a drain threshold does.
        Reads NEVER drain — GET/SIZE/CUTOFF serve the exact merged view
        host-side — but a read that must rebuild the drained base pays
        one device row gather, and over a tunneled chip one dispatch can
        cost ~100 ms: offload it too so it never stalls the event loop
        (the counter repos' foreign-GET pattern)."""
        if not args:
            return False
        op = args[0]
        if op in (b"TRIM", b"TRIMAT", b"CLR"):
            return True
        if op == b"INS" and len(args) >= 2:
            row = self._tbl.find(args[1])
            in_row = self._tbl.pend_len(row) if row >= 0 else 0
            return (
                in_row + 1 >= ROW_DRAIN_THRESHOLD
                or self._tbl.pend_rows_count() + 1 >= PENDING_DRAIN_THRESHOLD
            )
        if op in (b"GET", b"SIZE") and len(args) >= 2:
            row = self._tbl.find(args[1])
            if row < 0:
                return False
            if self._tbl.quiescent(row):
                if op == b"SIZE":
                    return False  # O(1) length-cache answer, no gather
                return (
                    row not in self._render
                    and self._tbl.len_cache(row) > 0
                    and not self._tbl.base_valid(row)  # a real device gather
                )
            return self._tbl.size(row) < 0  # gather only when base unknown
        return False

    def drain_overdue(self) -> bool:
        """Cluster converge path: after buffering a batch, the manager
        offloads the drain to a worker thread when any threshold trips.
        O(1): the table flags row-threshold crossings as it appends."""
        return (
            self._tbl.row_overdue()
            or self._tbl.pend_rows_count() >= PENDING_DRAIN_THRESHOLD
        )

    def flush_deltas(self):
        return self._tbl.flush_deltas()

    # -- sync digest (cluster/syncdigest.py) ---------------------------------

    def sync_dirty_keys(self) -> list[bytes]:
        return [self._tbl.key_of(r) for r in self._tbl.export_sync_dirty()]

    def sync_canon(self, key: bytes) -> bytes | None:
        """Canonical per-key state: the merged view (the exact post-drain
        lattice content, pending included) plus the grow-only cutoff —
        host-side except for the rare base-invalid row's one-row gather."""
        row = self._tbl.find(key)
        if row < 0:
            return None
        ents, cut = self._merged_view(row)
        if not ents and not cut:
            return None
        return repr((ents, cut)).encode()

    # -- snapshot (persist.py): full state in the wire-delta shape ----------

    def dump_state(self):
        self.drain()
        # one bulk device->host pull, then slice rows locally (a per-key
        # jitted gather would be O(keys) dispatches inside shutdown)
        st = self._state
        all_ts = tlog.decode_ts_np(
            None if st.nth is None else np.asarray(st.nth), np.asarray(st.ntl)
        )
        all_vid = tlog.decode_vid_np(np.asarray(st.nv))
        out = []
        for key, row in sorted(
            (self._tbl.key_of(r), r) for r in range(self._tbl.rows())
        ):
            length = self._tbl.len_cache(row)
            cutoff = self._tbl.cut_cache(row)
            entries = [
                (self._interner.lookup(int(all_vid[row, i])), int(all_ts[row, i]))
                for i in range(length)
            ]
            if entries or cutoff:
                out.append((key, (entries, cutoff)))
        return out

    def load_state(self, batch) -> None:
        for key, delta in batch:
            self.converge(key, delta)

    def _maybe_compact_interner(self) -> None:
        """Epoch compaction (weak-spot fix, VERDICT round 2): every value
        ever INSerted kept its interner slot after being trimmed away.
        Live ids are exactly the device rows' first `length` slots
        (canonical order scrubs the rest to -1), so pull the vid plane
        once, rebuild the table from the live set, and push the remapped
        plane back. Runs under the repo lock at drain time, before any
        new pending values intern."""
        # the native value interner compacts itself on the same cadence
        # (cheap floor check per drain; full walk only when it has grown)
        self._tbl.compact_values()
        live = self._tbl.live_total()  # O(1): maintained at finish_row
        if len(self._interner) <= 2 * live + COMPACT_SLACK:
            return
        lengths = {
            r: self._tbl.len_cache(r) for r in range(self._tbl.rows())
        }
        all_vid = tlog.decode_vid_np(np.asarray(self._state.nv))  # one pull
        rows = [
            all_vid[row, :length]
            for row, length in lengths.items()
            if length > 0
        ]
        flat = np.concatenate(rows) if rows else np.empty(0, np.int64)
        remap = self._interner.compact(flat[flat >= 0])
        new_vid = np.full(all_vid.shape, -1, np.int64)
        for row, length in lengths.items():
            if length > 0:
                src = all_vid[row, :length]
                # mask negatives on application exactly as on collection:
                # remap[-1] would silently alias the last live id
                new_vid[row, :length] = np.where(
                    src >= 0, remap[np.clip(src, 0, None)], -1
                )
        new_nv = tlog.encode_vid_np(new_vid)
        self._state = self._state._replace(
            nv=shard_plane(self._mesh, new_nv)
            if self._mesh is not None
            else jax.numpy.asarray(new_nv)
        )

    def _finish_drain(self, updates) -> None:
        """Common drain epilogue: refresh the per-row host caches from the
        kernel's (row, length, cutoff) read-backs, then clear pending."""
        for row, ln, ct in updates:
            self._render.pop(row, None)
            self._sorted.pop(row, None)
            self._tbl.finish_row(row, int(ln), int(ct))
        self._tbl.finish_drain_end()

    @timed_drain("TLOG", lambda self: self._tbl.touched_count())
    def drain(self, trim: tuple[int, int] | None = None) -> None:
        """Flush pending entries/cutoffs in one dispatch; with ``trim``
        = (row, count), the TRIM/CLR of that row fuses into the SAME
        dispatch via the kernel's per-row count column (counts of
        TRIM_NOOP leave other rows untouched)."""
        row_set = set(self._tbl.touched_rows())
        if not row_set and trim is None:
            return
        self._maybe_compact_interner()
        if trim is not None:
            row_set.add(trim[0])
        rows = sorted(row_set)
        pend = self._tbl.export_pend_bulk(rows)
        cuts_in = {r: self._tbl.pend_cutoff(r) for r in rows}
        # adaptive layout: the narrow (2-plane) state holds every ts below
        # TS32_MAX; the first wider timestamp or cutoff upgrades it
        # losslessly before this drain ships (mesh states start wide)
        if not self._state.wide and (
            any(ts > tlog.TS32_MAX for lst in pend.values() for ts, _ in lst)
            or any(c > tlog.TS32_MAX for c in cuts_in.values())
        ):
            self._state = tlog.widen(self._state)
        # capacity: keys, then entry slots (worst case current + pending)
        kcap = self._round_cap(bucket(max(self._tbl.rows(), 1), self._key_cap))
        need_len = max(
            self._tbl.len_cache(r) + len(pend.get(r, ())) for r in rows
        )
        lcap = bucket(max(need_len, 1), self._len_cap)
        if kcap != self._key_cap or lcap != self._len_cap:
            self._key_cap, self._len_cap = kcap, lcap
            self._state = self._place(tlog.grow(self._state, kcap, lcap))
        if self._mesh is not None:
            self._drain_sharded(rows, pend, cuts_in, trim)
            return
        while True:
            ld = bucket(max((len(pend.get(r, ())) for r in rows), default=1), 1)
            # dense path (repo_counters precedent): when the batch covers a
            # quarter of the keyspace and rows are narrow, aligned delta
            # rows skip the gather/scatter entirely
            dense = len(rows) * 4 >= self._key_cap and ld <= 64
            if dense:
                kc = self._key_cap
                d_ts = np.zeros((kc, ld), np.uint64)
                d_vid = np.full((kc, ld), -1, np.int64)
                d_cut = np.zeros(kc, np.uint64)
                for row in rows:
                    for j, (ts, value) in enumerate(pend.get(row, ())):
                        d_ts[row, j] = ts
                        d_vid[row, j] = self._interner.intern(value)
                    d_cut[row] = cuts_in.get(row, 0)
                tb = bucket(1)
                trim_ki = np.full(tb, PAD_ROW, np.int32)
                counts = np.full(tb, tlog.TRIM_NOOP, np.int64)
                if trim is not None:
                    trim_ki[0], counts[0] = trim
                new_state, ovf, lens, cuts = _drain_dense(
                    self._state, d_ts, d_vid, d_cut, trim_ki, counts
                )
                # check EVERY row: the dense kernel flags any row whose
                # entries reach into the tail columns the delta writes
                # through, including rows with no pending delta
                if bool(np.asarray(ovf).any()):
                    self._len_cap *= 2
                    self._state = tlog.grow(
                        self._state, self._key_cap, self._len_cap
                    )
                    continue
                self._state = new_state
                lens = np.asarray(lens)
                cuts = np.asarray(cuts)
                self._finish_drain((r, lens[r], cuts[r]) for r in rows)
                return
            b = bucket(len(rows))
            ki = np.full(b, PAD_ROW, np.int32)
            d_ts = np.zeros((b, ld), np.uint64)
            d_vid = np.full((b, ld), -1, np.int64)
            d_cut = np.zeros(b, np.uint64)
            counts = np.full(b, tlog.TRIM_NOOP, np.int64)
            for i, row in enumerate(rows):
                ki[i] = row
                for j, (ts, value) in enumerate(pend.get(row, ())):
                    d_ts[i, j] = ts
                    d_vid[i, j] = self._interner.intern(value)
                d_cut[i] = cuts_in.get(row, 0)
                if trim is not None and row == trim[0]:
                    counts[i] = trim[1]
            new_state, ovf, lens, cuts = _drain(
                self._state, ki, d_ts, d_vid, d_cut, counts
            )
            if bool(np.asarray(ovf)[: len(rows)].any()):
                # retry from the retained pre-merge state with doubled slots
                self._len_cap *= 2
                self._state = tlog.grow(self._state, self._key_cap, self._len_cap)
                continue
            self._state = new_state
            lens = np.asarray(lens)
            cuts = np.asarray(cuts)
            self._finish_drain(zip(rows, lens, cuts))
            return

    def _drain_sharded(self, rows, pend, cuts_in, trim=None) -> None:
        """Mesh-mode drain: per-row deltas route as u64 payload columns
        [ts(ld) | vid(ld) | cutoff | count]; the batched merge + fused
        trim runs per key block with per-slot lengths/cutoffs read back in
        the same launch. Same overflow-retry contract as the single-chip
        path."""
        import jax.numpy as jnp

        while True:
            ld = bucket(max((len(pend.get(r, ())) for r in rows), default=1), 1)
            payload = np.zeros((len(rows), 2 * ld + 2), np.uint64)
            # empty vid slots must read back as -1, not id 0
            payload[:, ld : 2 * ld] = np.uint64(0xFFFFFFFFFFFFFFFF)
            payload[:, 2 * ld + 1] = np.uint64(tlog.TRIM_NOOP)
            for i, row in enumerate(rows):
                for j, (ts, value) in enumerate(pend.get(row, ())):
                    payload[i, j] = ts
                    payload[i, ld + j] = self._interner.intern(value)
                payload[i, 2 * ld] = cuts_in.get(row, 0)
                if trim is not None and row == trim[0]:
                    payload[i, 2 * ld + 1] = trim[1]
            lr, pay, slots = route_drain64(
                np.asarray(rows, np.int64),
                payload,
                self._n_shards,
                self._key_cap // self._n_shards,
            )
            out = drain_sharded_tlog(
                self._mesh, *self._state, lr, jnp.asarray(pay), ld
            )
            ovf = np.asarray(out[5])
            if bool(ovf[slots >= 0].any()):
                # retry from the retained pre-merge state with doubled slots
                self._len_cap *= 2
                self._state = self._place(
                    tlog.grow(self._state, self._key_cap, self._len_cap)
                )
                continue
            self._state = tlog.TLogState(*out[:5])
            lens, cuts = np.asarray(out[6]), np.asarray(out[7])
            self._finish_drain(
                (int(g), lens[j], cuts[j])
                for j, g in enumerate(slots)
                if g >= 0
            )
            return
