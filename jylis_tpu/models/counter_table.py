"""Host-state backends for the counter repos.

The counters' host bookkeeping — key interning, own contributions,
serving-value cache, dirty/pending-own/foreign flags — lives behind one
small table interface with two implementations:

* `PyTable` — pure-Python dicts, the semantic oracle and the fallback
  when no C++ toolchain is available.
* `NativeTable` — a view over one table of the native counter engine
  (native/counter_engine.cpp via native/engine.py). The same state the
  server's native batch applier mutates, so commands applied natively
  and repo calls from Python see one source of truth.

Values are stored as u64 bit patterns; PNCOUNT decodes them as the
wrapped two's-complement i64 the reference's (p-n).i64() defines.
Polarity 0 is GCOUNT's only / PNCOUNT's P plane; polarity 1 is N.
"""

from __future__ import annotations

U64_MASK = (1 << 64) - 1


class PyTable:
    __slots__ = (
        "_keys", "_rkeys", "_value", "_own", "_ownset", "_pend", "_pendset",
        "_pend_rows", "_dirty", "_foreign", "_sync_dirty",
    )

    def __init__(self):
        self._keys: dict[bytes, int] = {}
        self._rkeys: list[bytes] = []
        self._value: list[int] = []  # u64 bits
        self._own = ([], [])  # per polarity, per row
        self._ownset = ([], [])
        self._pend = ([], [])
        self._pendset = ([], [])
        self._pend_rows: dict[int, None] = {}
        self._dirty: dict[int, None] = {}
        self._foreign: set[int] = set()
        self._sync_dirty: dict[int, None] = {}  # since last digest pass

    def rows(self) -> int:
        return len(self._rkeys)

    def upsert(self, key: bytes) -> int:
        row = self._keys.get(key)
        if row is None:
            row = len(self._rkeys)
            self._keys[key] = row
            self._rkeys.append(key)
            self._value.append(0)
            for pol in (0, 1):
                self._own[pol].append(0)
                self._ownset[pol].append(False)
                self._pend[pol].append(0)
                self._pendset[pol].append(False)
        return row

    def find(self, key: bytes) -> int:
        return self._keys.get(key, -1)

    def key_of(self, row: int) -> bytes:
        return self._rkeys[row]

    def inc(self, row: int, polarity: int, amount: int) -> None:
        own = (self._own[polarity][row] + amount) & U64_MASK
        self._own[polarity][row] = own
        self._ownset[polarity][row] = True
        if own > self._pend[polarity][row]:
            self._pend[polarity][row] = own
        if not (self._pendset[0][row] or self._pendset[1][row]):
            self._pend_rows[row] = None
        self._pendset[polarity][row] = True
        self._dirty[row] = None
        self._sync_dirty[row] = None
        delta = amount if polarity == 0 else -amount
        self._value[row] = (self._value[row] + delta) & U64_MASK

    def is_foreign(self, row: int) -> bool:
        return row in self._foreign

    def set_foreign(self, row: int) -> None:
        self._foreign.add(row)

    def value(self, row: int) -> int:
        return self._value[row]

    def own(self, row: int, polarity: int) -> int:
        return self._own[polarity][row]

    def own_max(self, row: int, polarity: int, v: int) -> None:
        if v > self._own[polarity][row]:
            self._own[polarity][row] = v
        self._ownset[polarity][row] = True

    def own_set(self, row: int) -> int:
        return (1 if self._ownset[0][row] else 0) | (
            2 if self._ownset[1][row] else 0
        )

    def apply_drain(self, rows, values) -> None:
        for row, v in zip(rows, values):
            self._value[row] = int(v) & U64_MASK
            self._foreign.discard(row)

    def pend_count(self) -> int:
        return len(self._pend_rows)

    def export_pending(self, clear: bool = True):
        rows = list(self._pend_rows)
        vp = [self._pend[0][r] if self._pendset[0][r] else 0 for r in rows]
        vn = [self._pend[1][r] if self._pendset[1][r] else 0 for r in rows]
        if clear:
            for r in rows:
                self._pend[0][r] = self._pend[1][r] = 0
                self._pendset[0][r] = self._pendset[1][r] = False
            self._pend_rows.clear()
        return rows, vp, vn

    def dirty_count(self) -> int:
        return len(self._dirty)

    def export_dirty(self):
        rows = list(self._dirty)
        op = [self._own[0][r] for r in rows]
        on = [self._own[1][r] for r in rows]
        sb = [self.own_set(r) for r in rows]
        self._dirty.clear()
        return rows, op, on, sb

    def export_sync_dirty(self) -> list[int]:
        rows = list(self._sync_dirty)
        self._sync_dirty.clear()
        return rows


class NativeTable:
    """One counter type's view over a shared native engine."""

    __slots__ = ("_eng", "_which")

    def __init__(self, engine, which: int):
        self._eng = engine
        self._which = which

    def rows(self) -> int:
        return self._eng.rows(self._which)

    def upsert(self, key: bytes) -> int:
        return self._eng.upsert(self._which, key)

    def find(self, key: bytes) -> int:
        return self._eng.find(self._which, key)

    def key_of(self, row: int) -> bytes:
        return self._eng.key_of(self._which, row)

    def inc(self, row: int, polarity: int, amount: int) -> None:
        self._eng.inc(self._which, row, polarity, amount)

    def is_foreign(self, row: int) -> bool:
        return self._eng.is_foreign(self._which, row)

    def set_foreign(self, row: int) -> None:
        self._eng.set_foreign(self._which, row)

    def value(self, row: int) -> int:
        return self._eng.value(self._which, row)

    def own(self, row: int, polarity: int) -> int:
        return self._eng.own(self._which, row, polarity)

    def own_max(self, row: int, polarity: int, v: int) -> None:
        self._eng.own_max(self._which, row, polarity, v)

    def own_set(self, row: int) -> int:
        return self._eng.own_set(self._which, row)

    def apply_drain(self, rows, values) -> None:
        self._eng.apply_drain(self._which, rows, values)

    def pend_count(self) -> int:
        return self._eng.pend_count(self._which)

    def export_pending(self, clear: bool = True):
        rows, vp, vn = self._eng.export_pending(self._which, clear=clear)
        return rows.tolist(), vp.tolist(), vn.tolist()

    def dirty_count(self) -> int:
        return self._eng.dirty_count(self._which)

    def export_dirty(self):
        rows, op, on, sb = self._eng.export_dirty(self._which)
        return rows.tolist(), op.tolist(), on.tolist(), sb.tolist()

    def export_sync_dirty(self) -> list[int]:
        return self._eng.export_sync_dirty(self._which)
