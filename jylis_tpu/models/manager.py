"""Per-type repo manager: dispatch, help-on-failure, proactive flush.

Reference analog: RepoManagerCore (repo_manager.pony:36-108). The actor
boundary becomes the asyncio event loop plus a per-repo asyncio.Lock;
what this class keeps is the behavioral contract:

* shutdown flag rejects new commands with the SHUTDOWN error (:49-55),
* parse failure renders the repo's help text (:62-66),
* a mutating command triggers a proactive delta flush, throttled to at
  most once per 500 ms per repo (:68-84),
* flush_deltas registers the delta sink and drains if non-empty (:86-90),
* clean_shutdown stops intake and performs a final flush (:95-108).

Concurrency (SURVEY.md §7(c) host↔device pipelining): commands that will
hit the device (the repo's ``may_drain`` predicate) run in a worker
thread via ``asyncio.to_thread`` so a multi-millisecond drain never
stalls the event loop — other repos' commands, other client connections,
and the cluster heartbeat all proceed. The per-repo lock is what the
one-actor-per-type boundary becomes: every repo access (apply, cluster
converge, heartbeat flush) serialises through it, so repo state is
never touched concurrently with an offloaded drain. FIFO holds among
lock-taking paths only — host-only commands take a lock-free inline
fast path when no drain is active (see apply_async), which preserves
per-connection order (the reference's guarantee) while
cross-connection interleaving stays unordered as it always was.
Replies from
offloaded commands are buffered and replayed on the loop thread
(transports are not thread-safe). The sync ``apply`` path remains for
single-threaded callers (warmup, persistence restore, direct-drive
tests and benchmarks).
"""

from __future__ import annotations

import asyncio
import time

from .base import ParseError
from .help import respond_help


class _ReplayResp:
    """Records resp-protocol calls in a worker thread; replays them on the
    event-loop thread afterwards."""

    __slots__ = ("calls",)

    def __init__(self):
        self.calls: list = []

    def __getattr__(self, name):
        def record(*args):
            self.calls.append((name, args))

        return record

    def replay(self, resp) -> None:
        for name, args in self.calls:
            getattr(resp, name)(*args)

PROACTIVE_FLUSH_INTERVAL = 0.5  # seconds; repo_manager.pony:80

SHUTDOWN_ERR = "SHUTDOWN (server is shutting down, rejecting all requests)"


class RepoManager:
    def __init__(
        self, name: str, repo, help_obj, clock=time.monotonic, served=None
    ):
        self.name = name
        self.repo = repo
        self.help = help_obj
        self._clock = clock
        # per-Database commands-served tally (SYSTEM METRICS "cmds");
        # the native engine counts its own settles in its own tables
        self._served = served if served is not None else {}
        self._deltas_fn = None
        self._last_proactive = None
        self._shutdown = False
        self._lock = asyncio.Lock()
        # admission control (Database.set_admission_cap): commands of
        # THIS class queued behind the repo lock past the cap are
        # refused with a typed BUSY instead of queuing without bound —
        # a hot key whose drains back the lock up degrades its own
        # command class, never the node. 0 = off (default). The
        # registry counts refusals (SERVING busy_refusals).
        self.admission_cap = 0
        self.registry = None
        self._inflight = 0
        # delta write-ahead journal (journal/journal.py), attached via
        # Database.set_journal: every flushed batch is handed to the
        # journal's writer thread before it reaches the network sink —
        # the hand-off itself runs under the same per-repo serialisation
        # the flush runs under (flush paths execute on the event loop
        # even when the apply was threaded), so journal order per repo
        # matches flush order
        self.journal = None

    def apply(self, resp, cmd: list[bytes]) -> None:
        """cmd includes the routing word (cmd[0] == data type name).
        Single-threaded path — see module docstring."""
        if self._shutdown:
            resp.err(SHUTDOWN_ERR)
            return
        if self._apply_core(resp, cmd):
            self._maybe_proactive_flush()

    def _apply_core(self, resp, cmd: list[bytes]) -> bool:
        self._served[self.name] = self._served.get(self.name, 0) + 1
        try:
            return self.repo.apply(resp, cmd[1:])
        except ParseError:
            respond_help(resp, self.help.render(cmd[1:]))
            return False

    async def apply_async(self, resp, cmd: list[bytes]) -> None:
        """Serving path: device-bound commands offload to a thread under
        the repo lock; host-only commands run inline.

        Fast path: when the lock is free (a threaded drain ALWAYS holds
        it, and releases only on the loop thread) and the command needs
        no device offload, apply synchronously with no await at all —
        the event loop is single-threaded, so the inline apply is atomic.
        This can barge ahead of waiters queued on the lock, so per-repo
        FIFO holds only among lock-taking paths; cross-connection
        interleaving is unordered anyway (lattice ops commute) and
        per-connection order is preserved by the server's sequential
        awaits."""
        if self._shutdown:
            resp.err(SHUTDOWN_ERR)
            return
        if not self._lock.locked():
            may = getattr(self.repo, "may_drain", None)
            if may is None or not may(cmd[1:]):
                if self._apply_core(resp, cmd):
                    self._maybe_proactive_flush()
                return
        if self.admission_cap and self._inflight >= self.admission_cap:
            # only lock-queued commands count as inflight (the inline
            # fast path above never queues), so the cap binds exactly
            # when this class is backed up behind its own drains
            if self.registry is not None:
                self.registry.note_serving("busy_refusals")
                self.registry.trace_event("serving", "busy", "", self.name)
            resp.err(
                f"BUSY ({self.name} admission cap {self.admission_cap} "
                "reached; this command class is backed up — retry)"
            )
            return
        self._inflight += 1
        try:
            async with self._lock:
                if self._shutdown:
                    # shutdown won the lock race while we queued behind a
                    # drain: the final flush already ran — accepting now
                    # would acknowledge a write that never replicates
                    resp.err(SHUTDOWN_ERR)
                    return
                may = getattr(self.repo, "may_drain", None)
                if may is not None and may(cmd[1:]):
                    replay = _ReplayResp()
                    changed = await asyncio.to_thread(
                        self._apply_core, replay, cmd
                    )
                    replay.replay(resp)
                else:
                    changed = self._apply_core(resp, cmd)
                if changed:
                    self._maybe_proactive_flush()
        finally:
            self._inflight -= 1

    # keys converged per event-loop slice: a multi-thousand-key batch (a
    # sync dump chunk, a post-load flush) converged in one go blocks the
    # loop long enough to slip heartbeats and Pongs past peers'
    # idle-eviction windows — the connection churn then LOSES deltas
    # (fire-and-forget). Slicing under the same lock keeps liveness
    # traffic flowing between slices with identical lattice results.
    CONVERGE_SLICE = 256

    async def converge_async(self, batch) -> None:
        async with self._lock:
            if self._shutdown:
                return  # fire-and-forget: late deltas re-deliver elsewhere
            batch = list(batch)
            for i in range(0, len(batch), self.CONVERGE_SLICE):
                self.converge_deltas(batch[i : i + self.CONVERGE_SLICE])
                if i + self.CONVERGE_SLICE < len(batch):
                    await asyncio.sleep(0)  # let pings/pongs interleave
            # threshold drains run AFTER buffering, in a worker thread —
            # never inline on the event loop; the post-state check is
            # exact where any pre-batch prediction can miss per-row sizes
            overdue = getattr(self.repo, "drain_overdue", None)
            if overdue is not None and overdue():
                await asyncio.to_thread(self.repo.drain)

    async def flush_async(self, fn) -> None:
        async with self._lock:
            # repos with banked native-queue work drain it in a worker
            # thread first (it can touch the device); the loop-side delta
            # flush then sees fully-applied state
            prep = getattr(self.repo, "prepare_flush", None)
            if prep is not None:
                await asyncio.to_thread(prep)
            self.flush_deltas(fn)

    def busy(self) -> bool:
        """True while a (possibly threaded) repo access holds the lock —
        the server's native fast path defers to Python while true."""
        return self._lock.locked()

    async def clean_shutdown_async(self) -> None:
        """Lock-holding shutdown: waits out any in-flight threaded drain,
        then stops intake and performs the final flush atomically."""
        self._shutdown = True  # reject commands queued behind the lock
        async with self._lock:
            prep = getattr(self.repo, "prepare_flush", None)
            if prep is not None:  # banked native-queue writes must ship
                await asyncio.to_thread(prep)
            if self._deltas_fn is not None:
                self.flush_deltas(self._deltas_fn)

    def _maybe_proactive_flush(self) -> None:
        if self._deltas_fn is None:
            return
        now = self._clock()
        if (
            self._last_proactive is None
            or now - self._last_proactive >= PROACTIVE_FLUSH_INTERVAL
        ):
            self._flush()
            self._last_proactive = now

    def _flush(self) -> None:
        # unconditional, like the reference's proactive path (:81)
        self._emit(self.repo.flush_deltas())

    def flush_deltas(self, fn) -> None:
        """Heartbeat entry point: registers the sink, drains if non-empty."""
        self._deltas_fn = fn
        if self.repo.deltas_size() > 0:
            self._emit(self.repo.flush_deltas())

    def _emit(self, batch) -> None:
        """Every flushed batch leaves through here: journal first (a
        batch that reached peers' lattices but not our disk is exactly
        the crash-loss gap the journal closes), then the network sink.
        The journal append only enqueues — encode/write/fsync happen on
        the journal's writer thread, off the serving path."""
        if self.journal is not None:
            self.journal.append(self.name, batch)
        if self.registry is not None and self.registry.enabled and batch:
            # per-digest-tree-bucket write heat: count each flushed key
            # against its sync_bucket (the SAME sha256(key)[0] the
            # anti-entropy digest tree shards by, database.py), so
            # SYSTEM OBSERVE can show where writes concentrate in the
            # tree — the placement telemetry ROADMAP item 3 needs.
            # Lazy import: database.py imports this module at load.
            from .database import sync_bucket

            note = self.registry.note_write_heat
            for key, _delta in batch:
                note(
                    self.name,
                    sync_bucket(
                        key if isinstance(key, bytes) else key.encode()
                    ),
                )
        self._deltas_fn((self.name, batch))

    def converge_deltas(self, batch) -> None:
        for key, delta in batch:
            self.repo.converge(key, delta)

    def clean_shutdown(self) -> None:
        self._shutdown = True
        prep = getattr(self.repo, "prepare_flush", None)
        if prep is not None:
            prep()
        if self._deltas_fn is not None:
            self.flush_deltas(self._deltas_fn)
