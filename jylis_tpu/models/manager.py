"""Per-type repo manager: dispatch, help-on-failure, proactive flush.

Reference analog: RepoManagerCore (repo_manager.pony:36-108). The actor
boundary becomes the asyncio event loop (one loop = strict per-node command
ordering, the same guarantee one Pony actor per type gave within a type);
what this class keeps is the behavioral contract:

* shutdown flag rejects new commands with the SHUTDOWN error (:49-55),
* parse failure renders the repo's help text (:62-66),
* a mutating command triggers a proactive delta flush, throttled to at
  most once per 500 ms per repo (:68-84),
* flush_deltas registers the delta sink and drains if non-empty (:86-90),
* clean_shutdown stops intake and performs a final flush (:95-108).
"""

from __future__ import annotations

import time

from .base import ParseError
from .help import respond_help

PROACTIVE_FLUSH_INTERVAL = 0.5  # seconds; repo_manager.pony:80

SHUTDOWN_ERR = "SHUTDOWN (server is shutting down, rejecting all requests)"


class RepoManager:
    def __init__(self, name: str, repo, help_obj, clock=time.monotonic):
        self.name = name
        self.repo = repo
        self.help = help_obj
        self._clock = clock
        self._deltas_fn = None
        self._last_proactive = None
        self._shutdown = False

    def apply(self, resp, cmd: list[bytes]) -> None:
        """cmd includes the routing word (cmd[0] == data type name)."""
        if self._shutdown:
            resp.err(SHUTDOWN_ERR)
            return
        try:
            changed = self.repo.apply(resp, cmd[1:])
        except ParseError:
            respond_help(resp, self.help.render(cmd[1:]))
            return
        if changed:
            self._maybe_proactive_flush()

    def _maybe_proactive_flush(self) -> None:
        if self._deltas_fn is None:
            return
        now = self._clock()
        if (
            self._last_proactive is None
            or now - self._last_proactive >= PROACTIVE_FLUSH_INTERVAL
        ):
            self._flush()
            self._last_proactive = now

    def _flush(self) -> None:
        # unconditional, like the reference's proactive path (:81)
        self._deltas_fn((self.name, self.repo.flush_deltas()))

    def flush_deltas(self, fn) -> None:
        """Heartbeat entry point: registers the sink, drains if non-empty."""
        self._deltas_fn = fn
        if self.repo.deltas_size() > 0:
            self._deltas_fn((self.name, self.repo.flush_deltas()))

    def converge_deltas(self, batch) -> None:
        for key, delta in batch:
            self.repo.converge(key, delta)

    def clean_shutdown(self) -> None:
        self._shutdown = True
        if self._deltas_fn is not None:
            self.flush_deltas(self._deltas_fn)
