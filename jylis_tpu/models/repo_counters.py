"""GCOUNT / PNCOUNT repos: device-resident counter keyspaces.

Reference analog: repo_gcount.pony:11-60 and repo_pncount.pony:12-67, where
each repo is a Map[key -> counter] and converge is a per-key loop. Here the
whole keyspace is ONE (keys x replicas) tensor per polarity (ops/gcount,
ops/pncount), and all mutations — local INCs and incoming anti-entropy
deltas alike — funnel into a coalesced pending batch that drains as a
single fused scatter-max + row-sum XLA call. The drain's row sums feed a
host value cache, so GET is a table lookup and the device only ever sees
large batches (the BASELINE.json north-star structure).

Host bookkeeping (keys, own contributions, value cache, dirty/pending/
foreign flags) lives behind the table backends in counter_table.py:
pure-Python dicts as the oracle, or the native C++ engine — the SAME
state the server's native batch applier (native/counter_engine.cpp)
mutates, so commands applied natively and Python-side drains/flushes
share one source of truth. Foreign delta columns (sparse per-replica
maps from the cluster) stay in Python dicts; they merge with the
exported pending-own values at drain time.

Delta wire shape: GCOUNT -> dict {replica_id: u64}; PNCOUNT -> a
(p_dict, n_dict) pair. Outbound deltas carry only this node's own column
(absolute values — joinable delta-state), which the table tracks exactly,
so flushes never need a device read.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np

from ..native.engine import G as ENG_G, PN as ENG_PN, resolve_engine
from ..ops import gcount, planes, pncount
from ..parallel import (
    drain_sharded_g,
    drain_sharded_pn,
    route_drain,
    serving_mesh,
    shard_plane,
)
from .base import ParseError, bucket, need, pad_rows, parse_u64, U64_MAX
from .counter_table import NativeTable, PyTable
from ..utils.metrics import timed_drain
from .help import RepoHelp

GCOUNT_HELP = RepoHelp("GCOUNT", {"GET": "key", "INC": "key value"})
PNCOUNT_HELP = RepoHelp(
    "PNCOUNT", {"GET": "key", "INC": "key value", "DEC": "key value"}
)


@partial(jax.jit, donate_argnums=0)
def _drain_g(state, ki, d_hi, d_lo):
    st = gcount.converge_batch(state, ki, d_hi, d_lo)
    return st, gcount.read(st, ki)


@partial(jax.jit, donate_argnums=0)
def _drain_pn(state, ki, dp_hi, dp_lo, dn_hi, dn_lo):
    st = pncount.converge_batch(state, ki, dp_hi, dp_lo, dn_hi, dn_lo)
    return st, pncount.read(st, ki)


# dense drains: when a batch covers most of the keyspace (a full
# anti-entropy sweep), an elementwise join streams each plane once instead
# of paying random-access gathers + scatters twice per plane
@partial(jax.jit, donate_argnums=0)
def _drain_g_dense(state, d_hi, d_lo):
    st = gcount.join(state, gcount.GCountState(d_hi, d_lo))
    return st, gcount.read_all(st)


@partial(jax.jit, donate_argnums=0)
def _drain_pn_dense(state, dp_hi, dp_lo, dn_hi, dn_lo):
    st = pncount.join(state, pncount.PNCountState(dp_hi, dp_lo, dn_hi, dn_lo))
    return st, pncount.read_all(st)


# a batch covering >= 1/DENSE_FRACTION of the keyspace drains dense: the
# sparse composite's random accesses cost far more per row than streaming
DENSE_FRACTION = 4


def _wrap_i64(v: int) -> int:
    """Wrap into signed-64 range (the reference's modular (p-n).i64())."""
    return ((v + (1 << 63)) & U64_MAX) - (1 << 63)


class _CounterRepo:
    """Shared machinery; subclasses bind the ops module and command set."""

    _which: int  # native engine table id

    def __init__(
        self,
        identity: int,
        key_cap: int = 1024,
        rep_cap: int = 8,
        mesh="auto",
        engine="auto",
    ):
        self._identity = identity
        self._rids: dict[int, int] = {}  # replica id -> column
        # mesh mode (SURVEY.md §5.8): with >1 visible device the keyspace
        # planes live keys-sharded over the serving mesh and drains route
        # through parallel/sharded — the per-type actor keyspace of
        # repo_manager.pony:92-93 become per-device key blocks. With one
        # device (the real tunneled chip) this resolves to None and the
        # single-chip fast path below is untouched.
        self._mesh = serving_mesh() if mesh == "auto" else mesh
        self._n_shards = self._mesh.devices.size if self._mesh is not None else 1
        self._key_cap = self._round_cap(key_cap)
        self._rep_cap = rep_cap
        self.engine = engine = resolve_engine(engine)  # shared when set
        self._tbl = (
            NativeTable(engine, self._which) if engine is not None else PyTable()
        )
        # foreign delta columns buffered per row per polarity (sparse
        # {col: max-value} maps from cluster converges)
        self._pending_f: tuple[dict[int, dict[int, int]], ...] = ({}, {})
        # sync-digest bookkeeping (cluster/syncdigest): a CUMULATIVE join
        # of every foreign column ever converged, keyed by replica id —
        # unlike _pending_f it never clears, so the per-key canonical
        # state (own ⊔ foreign) reads host-side with no device pull
        self._sync_f: tuple[dict[int, dict[int, int]], ...] = ({}, {})
        self._sync_dirty_extra: set[int] = set()  # converge-path rows

    def _get_raw(self, key: bytes) -> int:
        """Serving value bits for a key (drains first when foreign deltas
        make the cache stale; local writes keep it exact)."""
        row = self._tbl.find(key)
        if row < 0:
            return 0
        if self._tbl.is_foreign(row):
            self.drain()
        return self._tbl.value(row)

    def _col_for(self, rid: int) -> int:
        col = self._rids.get(rid)
        if col is None:
            col = len(self._rids)
            self._rids[rid] = col
        return col

    def _round_cap(self, k: int) -> int:
        """Key capacity must split evenly over the mesh's keys axis."""
        ns = self._n_shards
        return -(-k // ns) * ns

    def _place(self, state):
        """(Re-)place state planes keys-sharded when a mesh is active."""
        if self._mesh is None:
            return state
        return type(state)(*(shard_plane(self._mesh, p) for p in state))

    def _grow_to_fit(self) -> None:
        k = self._round_cap(bucket(max(self._tbl.rows(), 1), self._key_cap))
        r = bucket(max(len(self._rids), 1), self._rep_cap)
        if k != self._key_cap or r != self._rep_cap:
            self._key_cap, self._rep_cap = k, r
            self._state = self._place(self._ops.grow(self._state, k, r))

    def deltas_size(self) -> int:
        return self._tbl.dirty_count()

    def may_drain(self, args: list[bytes]) -> bool:
        """Will this command hit the device? Only a GET over a row holding
        un-drained FOREIGN deltas does (local writes keep the host value
        cache exact); the server offloads such commands to a thread."""
        if len(args) < 2 or args[0] != b"GET":
            return False
        row = self._tbl.find(args[1])
        return row >= 0 and self._tbl.is_foreign(row)

    def _pend_size(self) -> int:
        """Exact drain batch size: own-pending rows unioned with the
        buffered foreign rows (metrics, read before the drain runs)."""
        own_rows, _vp, _vn = self._tbl.export_pending(clear=False)
        rows = set(own_rows)
        rows.update(self._pending_f[0])
        rows.update(self._pending_f[1])
        return len(rows)

    def converge_polarity(self, key: bytes, polarity: int, delta: dict) -> None:
        row = self._tbl.upsert(key)
        p = self._pending_f[polarity].setdefault(row, {})
        sf = self._sync_f[polarity].setdefault(row, {})
        for rid, v in delta.items():
            col = self._col_for(rid)
            if v > p.get(col, 0):
                p[col] = v
            if v > sf.get(rid, 0):
                sf[rid] = v
        self._sync_dirty_extra.add(row)
        self._tbl.set_foreign(row)

    def _collect_rows(self):
        """The drain batch: pending-own values merged with the buffered
        foreign columns -> (rows, per-row {col: val} per polarity).
        Reads WITHOUT clearing: the window clears in `_finish_drain`, so
        a device failure mid-drain keeps every contribution for the
        retry (the old dict path's exception-safety contract)."""
        own_rows, vp, vn = self._tbl.export_pending(clear=False)
        own_col = self._col_for(self._identity)
        per_pol: tuple[dict[int, dict[int, int]], ...] = ({}, {})
        for pol, own_vals in ((0, vp), (1, vn)):
            fdict = self._pending_f[pol]
            for row, v in zip(own_rows, own_vals):
                if v:
                    per_pol[pol][row] = {own_col: v}
            for row, cols in fdict.items():
                d = per_pol[pol].setdefault(row, {})
                for col, v in cols.items():
                    if v > d.get(col, 0):
                        d[col] = v
        rows = list(dict.fromkeys(list(per_pol[0]) + list(per_pol[1])))
        return rows, per_pol

    def _finish_drain(self, rows, values_bits) -> None:
        self._tbl.apply_drain(rows, values_bits)
        self._tbl.export_pending(clear=True)  # drain succeeded: clear window
        self._pending_f[0].clear()
        self._pending_f[1].clear()

    # -- sync digest (cluster/syncdigest.py) ---------------------------------

    def sync_dirty_keys(self) -> list[bytes]:
        """Keys whose canonical state may have changed since the last
        digest pass (native INC/DEC fast path ∪ converge/load); clears."""
        rows = set(self._tbl.export_sync_dirty())
        rows.update(self._sync_dirty_extra)
        self._sync_dirty_extra.clear()
        return [self._tbl.key_of(r) for r in rows]

    def _sync_cols(self, row: int, polarity: int) -> list[tuple[int, int]]:
        """{rid: max} for one polarity: own contribution ⊔ the cumulative
        foreign mirror — exactly the column state the device converges
        to, with no device read."""
        d = dict(self._sync_f[polarity].get(row, ()))
        if self._tbl.own_set(row) & (1 << polarity):
            own = self._tbl.own(row, polarity)
            if own > d.get(self._identity, 0):
                d[self._identity] = own
        return sorted((rid, v) for rid, v in d.items() if v)

    # -- snapshot plumbing shared by both types ------------------------------

    def _sorted_keys(self):
        return sorted(
            (self._tbl.key_of(r), r) for r in range(self._tbl.rows())
        )


class RepoGCOUNT(_CounterRepo):
    name = "GCOUNT"
    help = GCOUNT_HELP
    _ops = gcount
    _which = ENG_G

    def __init__(self, identity: int, **kw):
        super().__init__(identity, **kw)
        self._state = self._place(gcount.init(self._key_cap, self._rep_cap))

    def _get_value(self, key: bytes) -> int:
        return self._get_raw(key)

    def sync_canon(self, key: bytes) -> bytes | None:
        row = self._tbl.find(key)
        if row < 0:
            return None
        cols = self._sync_cols(row, 0)
        return repr(cols).encode() if cols else None

    # -- commands (repo_gcount.pony:25-60) ---------------------------------

    def apply(self, resp, args: list[bytes]) -> bool:
        op = need(args, 0)
        if op == b"GET":
            resp.u64(self._get_value(need(args, 1)))
            return False
        if op == b"INC":
            key = need(args, 1)
            amount = parse_u64(need(args, 2))
            self._tbl.inc(self._tbl.upsert(key), 0, amount)
            resp.ok()
            return True
        raise ParseError()

    # -- lattice plumbing ---------------------------------------------------

    def converge(self, key: bytes, delta: dict) -> None:
        self.converge_polarity(key, 0, delta)

    @timed_drain("GCOUNT", _CounterRepo._pend_size)
    def drain(self) -> None:
        rows, per_pol = self._collect_rows()
        if not rows:
            return
        self._grow_to_fit()
        pending = per_pol[0]
        if self._mesh is not None:
            deltas = np.zeros((len(rows), self._rep_cap), np.uint64)
            for i, row in enumerate(rows):
                for col, v in pending.get(row, {}).items():
                    deltas[i, col] = v
            lr, d_hi, d_lo, slots = route_drain(
                np.asarray(rows, np.int64),
                deltas,
                self._n_shards,
                self._key_cap // self._n_shards,
            )
            hi, lo, sums = drain_sharded_g(
                self._mesh, self._state.hi, self._state.lo, lr, d_hi, d_lo
            )
            self._state = gcount.GCountState(hi, lo)
            sums = np.asarray(sums)
            live = [(int(g), sums[j]) for j, g in enumerate(slots) if g >= 0]
            self._finish_drain([r for r, _ in live], [v for _, v in live])
        elif len(rows) * DENSE_FRACTION >= self._key_cap:
            dense = np.zeros((self._key_cap, self._rep_cap), np.uint64)
            for row in rows:
                for col, v in pending.get(row, {}).items():
                    dense[row, col] = v
            d_hi, d_lo = planes.split64_np(dense)
            self._state, sums = _drain_g_dense(self._state, d_hi, d_lo)
            sums = np.asarray(sums)
            self._finish_drain(rows, [sums[row] for row in rows])
        else:
            b = bucket(len(rows))
            ki = pad_rows(b)
            ki[: len(rows)] = rows
            deltas = np.zeros((b, self._rep_cap), np.uint64)
            for i, row in enumerate(rows):
                for col, v in pending.get(row, {}).items():
                    deltas[i, col] = v
            d_hi, d_lo = planes.split64_np(deltas)
            self._state, sums = _drain_g(self._state, ki, d_hi, d_lo)
            sums = np.asarray(sums)
            self._finish_drain(rows, [sums[i] for i in range(len(rows))])

    def flush_deltas(self):
        rows, op, _on, _sb = self._tbl.export_dirty()
        out = sorted(
            (self._tbl.key_of(r), {self._identity: int(v)})
            for r, v in zip(rows, op)
        )
        return out

    # -- snapshot (persist.py): full state in the wire-delta shape ----------

    def dump_state(self):
        self.drain()
        counts = gcount.to_counts(self._state)
        # jlint: order-ok — builds a col->rid LOOKUP map (order unused);
        # the wire encoder sorts every span by rid before any byte ships
        cols = {col: rid for rid, col in self._rids.items()}
        out = []
        for key, row in self._sorted_keys():
            d = {
                cols[c]: int(v)
                for c, v in enumerate(counts[row, : len(cols)])
                if v
            }
            if d:
                out.append((key, d))
        return out

    def load_state(self, batch) -> None:
        for key, delta in batch:
            self.converge(key, delta)
            # my own column is my private monotonic state: losing it would
            # make future INCs disappear under the pending max
            # jlint: ridbranch-ok — boot-only own-column repair; the
            # lattice value converged above is identity-independent
            if self._identity in delta:
                self._tbl.own_max(
                    self._tbl.upsert(key), 0, delta[self._identity]
                )


class RepoPNCOUNT(_CounterRepo):
    name = "PNCOUNT"
    help = PNCOUNT_HELP
    _ops = pncount
    _which = ENG_PN

    def __init__(self, identity: int, **kw):
        super().__init__(identity, **kw)
        self._state = self._place(pncount.init(self._key_cap, self._rep_cap))

    def _get_value(self, key: bytes) -> int:
        return _wrap_i64(self._get_raw(key))

    def sync_canon(self, key: bytes) -> bytes | None:
        row = self._tbl.find(key)
        if row < 0:
            return None
        p = self._sync_cols(row, 0)
        n = self._sync_cols(row, 1)
        return repr((p, n)).encode() if p or n else None

    # -- commands (repo_pncount.pony:26-67) --------------------------------

    def apply(self, resp, args: list[bytes]) -> bool:
        op = need(args, 0)
        if op == b"GET":
            resp.i64(self._get_value(need(args, 1)))
            return False
        if op in (b"INC", b"DEC"):
            key = need(args, 1)
            amount = parse_u64(need(args, 2))
            self._tbl.inc(
                self._tbl.upsert(key), 0 if op == b"INC" else 1, amount
            )
            resp.ok()
            return True
        raise ParseError()

    def converge(self, key: bytes, delta: tuple) -> None:
        dp, dn = delta
        self.converge_polarity(key, 0, dp)
        self.converge_polarity(key, 1, dn)

    @timed_drain("PNCOUNT", _CounterRepo._pend_size)
    def drain(self) -> None:
        rows, per_pol = self._collect_rows()
        if not rows:
            return
        self._grow_to_fit()
        pend_p, pend_n = per_pol
        if self._mesh is not None:
            # polarity-stacked (B, 2R) so one routing pass serves both
            stacked = np.zeros((len(rows), 2 * self._rep_cap), np.uint64)
            r = self._rep_cap
            for i, row in enumerate(rows):
                for col, v in pend_p.get(row, {}).items():
                    stacked[i, col] = v
                for col, v in pend_n.get(row, {}).items():
                    stacked[i, r + col] = v
            lr, d_hi, d_lo, slots = route_drain(
                np.asarray(rows, np.int64),
                stacked,
                self._n_shards,
                self._key_cap // self._n_shards,
            )
            p_hi, p_lo, n_hi, n_lo, sums = drain_sharded_pn(
                self._mesh, *self._state, lr, d_hi, d_lo
            )
            self._state = pncount.PNCountState(p_hi, p_lo, n_hi, n_lo)
            sums = np.asarray(sums).view(np.uint64)
            live = [(int(g), sums[j]) for j, g in enumerate(slots) if g >= 0]
            self._finish_drain([r for r, _ in live], [v for _, v in live])
        elif len(rows) * DENSE_FRACTION >= self._key_cap:
            dp = np.zeros((self._key_cap, self._rep_cap), np.uint64)
            dn = np.zeros((self._key_cap, self._rep_cap), np.uint64)
            for row in rows:
                for col, v in pend_p.get(row, {}).items():
                    dp[row, col] = v
                for col, v in pend_n.get(row, {}).items():
                    dn[row, col] = v
            dp_hi, dp_lo = planes.split64_np(dp)
            dn_hi, dn_lo = planes.split64_np(dn)
            self._state, sums = _drain_pn_dense(
                self._state, dp_hi, dp_lo, dn_hi, dn_lo
            )
            sums = np.asarray(sums).view(np.uint64)
            self._finish_drain(rows, [sums[row] for row in rows])
        else:
            b = bucket(len(rows))
            ki = pad_rows(b)
            ki[: len(rows)] = rows
            dp = np.zeros((b, self._rep_cap), np.uint64)
            dn = np.zeros((b, self._rep_cap), np.uint64)
            for i, row in enumerate(rows):
                for col, v in pend_p.get(row, {}).items():
                    dp[i, col] = v
                for col, v in pend_n.get(row, {}).items():
                    dn[i, col] = v
            dp_hi, dp_lo = planes.split64_np(dp)
            dn_hi, dn_lo = planes.split64_np(dn)
            self._state, sums = _drain_pn(
                self._state, ki, dp_hi, dp_lo, dn_hi, dn_lo
            )
            sums = np.asarray(sums).view(np.uint64)
            self._finish_drain(rows, [sums[i] for i in range(len(rows))])

    def flush_deltas(self):
        rows, op, on, sb = self._tbl.export_dirty()
        out = []
        for r, p, n, bits in zip(rows, op, on, sb):
            dp = {self._identity: int(p)} if bits & 1 else {}
            dn = {self._identity: int(n)} if bits & 2 else {}
            out.append((self._tbl.key_of(r), (dp, dn)))
        out.sort()
        return out

    # -- snapshot (persist.py): full state in the wire-delta shape ----------

    def dump_state(self):
        self.drain()
        # jlint: order-ok — builds a col->rid LOOKUP map (order unused);
        # the wire encoder sorts every span by rid before any byte ships
        cols = {col: rid for rid, col in self._rids.items()}
        p = planes.combine64_np(
            np.asarray(self._state.p_hi), np.asarray(self._state.p_lo)
        )
        n = planes.combine64_np(
            np.asarray(self._state.n_hi), np.asarray(self._state.n_lo)
        )
        out = []
        for key, row in self._sorted_keys():
            dp = {cols[c]: int(v) for c, v in enumerate(p[row, : len(cols)]) if v}
            dn = {cols[c]: int(v) for c, v in enumerate(n[row, : len(cols)]) if v}
            if dp or dn:
                out.append((key, (dp, dn)))
        return out

    def load_state(self, batch) -> None:
        for key, (dp, dn) in batch:
            self.converge(key, (dp, dn))
            row = self._tbl.upsert(key)
            # jlint: ridbranch-ok — boot-only own-column repair (above)
            if self._identity in dp:
                self._tbl.own_max(row, 0, dp[self._identity])
            # jlint: ridbranch-ok — boot-only own-column repair (above)
            if self._identity in dn:
                self._tbl.own_max(row, 1, dn[self._identity])
