"""GCOUNT / PNCOUNT repos: device-resident counter keyspaces.

Reference analog: repo_gcount.pony:11-60 and repo_pncount.pony:12-67, where
each repo is a Map[key -> counter] and converge is a per-key loop. Here the
whole keyspace is ONE (keys x replicas) tensor per polarity (ops/gcount,
ops/pncount), and all mutations — local INCs and incoming anti-entropy
deltas alike — funnel into a coalesced pending batch that drains as a
single fused scatter-max + row-sum XLA call. The drain's row sums feed a
host cache, so GET is a host dict lookup and the device only ever sees
large batches (the BASELINE.json north-star structure).

Delta wire shape: GCOUNT -> dict {replica_id: u64}; PNCOUNT -> a
(p_dict, n_dict) pair. Outbound deltas carry only this node's own column
(absolute values — joinable delta-state), which the host tracks exactly,
so flushes never need a device read.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np

from ..ops import gcount, planes, pncount
from ..parallel import (
    drain_sharded_g,
    drain_sharded_pn,
    route_drain,
    serving_mesh,
    shard_plane,
)
from .base import ParseError, bucket, need, pad_rows, parse_u64, U64_MAX
from ..utils.metrics import timed_drain
from .help import RepoHelp

GCOUNT_HELP = RepoHelp("GCOUNT", {"GET": "key", "INC": "key value"})
PNCOUNT_HELP = RepoHelp(
    "PNCOUNT", {"GET": "key", "INC": "key value", "DEC": "key value"}
)


@partial(jax.jit, donate_argnums=0)
def _drain_g(state, ki, d_hi, d_lo):
    st = gcount.converge_batch(state, ki, d_hi, d_lo)
    return st, gcount.read(st, ki)


@partial(jax.jit, donate_argnums=0)
def _drain_pn(state, ki, dp_hi, dp_lo, dn_hi, dn_lo):
    st = pncount.converge_batch(state, ki, dp_hi, dp_lo, dn_hi, dn_lo)
    return st, pncount.read(st, ki)


# dense drains: when a batch covers most of the keyspace (a full
# anti-entropy sweep), an elementwise join streams each plane once instead
# of paying random-access gathers + scatters twice per plane
@partial(jax.jit, donate_argnums=0)
def _drain_g_dense(state, d_hi, d_lo):
    st = gcount.join(state, gcount.GCountState(d_hi, d_lo))
    return st, gcount.read_all(st)


@partial(jax.jit, donate_argnums=0)
def _drain_pn_dense(state, dp_hi, dp_lo, dn_hi, dn_lo):
    st = pncount.join(state, pncount.PNCountState(dp_hi, dp_lo, dn_hi, dn_lo))
    return st, pncount.read_all(st)


# a batch covering >= 1/DENSE_FRACTION of the keyspace drains dense: the
# sparse composite's random accesses cost far more per row than streaming
DENSE_FRACTION = 4


def _wrap_i64(v: int) -> int:
    """Wrap into signed-64 range (the reference's modular (p-n).i64())."""
    return ((v + (1 << 63)) & U64_MAX) - (1 << 63)


class _CounterRepo:
    """Shared machinery; subclasses bind the ops module and command set."""

    def __init__(
        self, identity: int, key_cap: int = 1024, rep_cap: int = 8, mesh="auto"
    ):
        self._identity = identity
        self._keys: dict[bytes, int] = {}  # key -> row
        self._rids: dict[int, int] = {}  # replica id -> column
        # mesh mode (SURVEY.md §5.8): with >1 visible device the keyspace
        # planes live keys-sharded over the serving mesh and drains route
        # through parallel/sharded — the per-type actor keyspace of
        # repo_manager.pony:92-93 become per-device key blocks. With one
        # device (the real tunneled chip) this resolves to None and the
        # single-chip fast path below is untouched.
        self._mesh = serving_mesh() if mesh == "auto" else mesh
        self._n_shards = self._mesh.devices.size if self._mesh is not None else 1
        self._key_cap = self._round_cap(key_cap)
        self._rep_cap = rep_cap
        self._values: dict[int, int] = {}  # row -> cached serving value
        self._dirty: set[bytes] = set()  # keys with unflushed deltas
        # rows whose pending batch contains FOREIGN deltas: only those make
        # the host value cache stale. Local INC/DEC adjust the cache
        # eagerly and exactly (own columns are private and monotone), so a
        # GET after purely-local writes never needs a device round-trip —
        # the read-your-writes host shadow from SURVEY.md section 7(c).
        self._foreign: set[int] = set()

    def _get_value(self, key: bytes) -> int:
        row = self._keys.get(key)
        if row is None:
            return 0
        if row in self._foreign:
            self.drain()
        return self._values.get(row, 0)

    def _row_for(self, key: bytes) -> int:
        row = self._keys.get(key)
        if row is None:
            row = len(self._keys)
            self._keys[key] = row
        return row

    def _col_for(self, rid: int) -> int:
        col = self._rids.get(rid)
        if col is None:
            col = len(self._rids)
            self._rids[rid] = col
        return col

    def _round_cap(self, k: int) -> int:
        """Key capacity must split evenly over the mesh's keys axis."""
        ns = self._n_shards
        return -(-k // ns) * ns

    def _place(self, state):
        """(Re-)place state planes keys-sharded when a mesh is active."""
        if self._mesh is None:
            return state
        return type(state)(*(shard_plane(self._mesh, p) for p in state))

    def _grow_to_fit(self) -> None:
        k = self._round_cap(bucket(max(len(self._keys), 1), self._key_cap))
        r = bucket(max(len(self._rids), 1), self._rep_cap)
        if k != self._key_cap or r != self._rep_cap:
            self._key_cap, self._rep_cap = k, r
            self._state = self._place(self._ops.grow(self._state, k, r))

    def deltas_size(self) -> int:
        return len(self._dirty)

    def may_drain(self, args: list[bytes]) -> bool:
        """Will this command hit the device? Only a GET over a row holding
        un-drained FOREIGN deltas does (local writes keep the host value
        cache exact); the server offloads such commands to a thread."""
        if len(args) < 2 or args[0] != b"GET":
            return False
        row = self._keys.get(args[1])
        return row is not None and row in self._foreign


class RepoGCOUNT(_CounterRepo):
    name = "GCOUNT"
    help = GCOUNT_HELP
    _ops = gcount

    def __init__(self, identity: int, **kw):
        super().__init__(identity, **kw)
        self._state = self._place(gcount.init(self._key_cap, self._rep_cap))
        self._own: dict[bytes, int] = {}  # my column, absolute (u64 wrap)
        self._pending: dict[int, dict[int, int]] = {}  # row -> col -> max val

    # -- commands (repo_gcount.pony:25-60) ---------------------------------

    def apply(self, resp, args: list[bytes]) -> bool:
        op = need(args, 0)
        if op == b"GET":
            resp.u64(self._get_value(need(args, 1)))
            return False
        if op == b"INC":
            key = need(args, 1)
            amount = parse_u64(need(args, 2))
            self._inc(key, amount)
            resp.ok()
            return True
        raise ParseError()

    def _inc(self, key: bytes, amount: int) -> None:
        new = (self._own.get(key, 0) + amount) & U64_MAX
        self._own[key] = new
        col = self._col_for(self._identity)
        row = self._row_for(key)
        p = self._pending.setdefault(row, {})
        p[col] = max(p.get(col, 0), new)
        self._dirty.add(key)
        # own column grew by exactly `amount`: adjust the value cache
        self._values[row] = (self._values.get(row, 0) + amount) & U64_MAX

    # -- lattice plumbing ---------------------------------------------------

    def converge(self, key: bytes, delta: dict) -> None:
        row = self._row_for(key)
        p = self._pending.setdefault(row, {})
        for rid, v in delta.items():
            col = self._col_for(rid)
            if v > p.get(col, 0):
                p[col] = v
        self._foreign.add(row)

    @timed_drain("GCOUNT", lambda self: len(self._pending))
    def drain(self) -> None:
        if not self._pending:
            return
        self._grow_to_fit()
        rows = list(self._pending)  # dict keys: unique, as converge requires
        if self._mesh is not None:
            deltas = np.zeros((len(rows), self._rep_cap), np.uint64)
            for i, row in enumerate(rows):
                for col, v in self._pending[row].items():
                    deltas[i, col] = v
            lr, d_hi, d_lo, slots = route_drain(
                np.asarray(rows, np.int64),
                deltas,
                self._n_shards,
                self._key_cap // self._n_shards,
            )
            hi, lo, sums = drain_sharded_g(
                self._mesh, self._state.hi, self._state.lo, lr, d_hi, d_lo
            )
            self._state = gcount.GCountState(hi, lo)
            sums = np.asarray(sums)
            for j, g in enumerate(slots):
                if g >= 0:
                    self._values[int(g)] = int(sums[j])
        elif len(rows) * DENSE_FRACTION >= self._key_cap:
            dense = np.zeros((self._key_cap, self._rep_cap), np.uint64)
            for row in rows:
                for col, v in self._pending[row].items():
                    dense[row, col] = v
            d_hi, d_lo = planes.split64_np(dense)
            self._state, sums = _drain_g_dense(self._state, d_hi, d_lo)
            sums = np.asarray(sums)
            for row in rows:
                self._values[row] = int(sums[row])
        else:
            b = bucket(len(rows))
            ki = pad_rows(b)
            ki[: len(rows)] = rows
            deltas = np.zeros((b, self._rep_cap), np.uint64)
            for i, row in enumerate(rows):
                for col, v in self._pending[row].items():
                    deltas[i, col] = v
            d_hi, d_lo = planes.split64_np(deltas)
            self._state, sums = _drain_g(self._state, ki, d_hi, d_lo)
            sums = np.asarray(sums)
            for i, row in enumerate(rows):
                self._values[row] = int(sums[i])
        self._pending.clear()
        self._foreign.clear()

    def flush_deltas(self):
        out = [
            (k, {self._identity: self._own[k]}) for k in sorted(self._dirty)
        ]
        self._dirty.clear()
        return out

    # -- snapshot (persist.py): full state in the wire-delta shape ----------

    def dump_state(self):
        self.drain()
        counts = gcount.to_counts(self._state)
        cols = {col: rid for rid, col in self._rids.items()}
        out = []
        for key, row in sorted(self._keys.items()):
            d = {
                cols[c]: int(v)
                for c, v in enumerate(counts[row, : len(cols)])
                if v
            }
            if d:
                out.append((key, d))
        return out

    def load_state(self, batch) -> None:
        for key, delta in batch:
            self.converge(key, delta)
            # my own column is my private monotonic state: losing it would
            # make future INCs disappear under the pending max
            if self._identity in delta:
                self._own[key] = max(
                    self._own.get(key, 0), delta[self._identity]
                )


class RepoPNCOUNT(_CounterRepo):
    name = "PNCOUNT"
    help = PNCOUNT_HELP
    _ops = pncount

    def __init__(self, identity: int, **kw):
        super().__init__(identity, **kw)
        self._state = self._place(pncount.init(self._key_cap, self._rep_cap))
        self._own_p: dict[bytes, int] = {}
        self._own_n: dict[bytes, int] = {}
        # row -> (col -> max val), one map per polarity
        self._pending_p: dict[int, dict[int, int]] = {}
        self._pending_n: dict[int, dict[int, int]] = {}

    # -- commands (repo_pncount.pony:26-67) --------------------------------

    def apply(self, resp, args: list[bytes]) -> bool:
        op = need(args, 0)
        if op == b"GET":
            resp.i64(self._get_value(need(args, 1)))
            return False
        if op in (b"INC", b"DEC"):
            key = need(args, 1)
            amount = parse_u64(need(args, 2))
            own, pend = (
                (self._own_p, self._pending_p)
                if op == b"INC"
                else (self._own_n, self._pending_n)
            )
            new = (own.get(key, 0) + amount) & U64_MAX
            own[key] = new
            col = self._col_for(self._identity)
            row = self._row_for(key)
            p = pend.setdefault(row, {})
            p[col] = max(p.get(col, 0), new)
            self._dirty.add(key)
            # exact eager adjust, wrapped to the signed-64 read domain
            signed = amount if op == b"INC" else -amount
            self._values[row] = _wrap_i64(self._values.get(row, 0) + signed)
            resp.ok()
            return True
        raise ParseError()

    def converge(self, key: bytes, delta: tuple) -> None:
        dp, dn = delta
        row = self._row_for(key)
        for pend, d in ((self._pending_p, dp), (self._pending_n, dn)):
            p = pend.setdefault(row, {})
            for rid, v in d.items():
                col = self._col_for(rid)
                if v > p.get(col, 0):
                    p[col] = v
        self._foreign.add(row)

    @timed_drain(
        "PNCOUNT",
        lambda self: len(set(self._pending_p) | set(self._pending_n)),
    )
    def drain(self) -> None:
        if not self._pending_p and not self._pending_n:
            return
        self._grow_to_fit()
        rows = sorted(set(self._pending_p) | set(self._pending_n))
        if self._mesh is not None:
            # polarity-stacked (B, 2R) so one routing pass serves both
            stacked = np.zeros((len(rows), 2 * self._rep_cap), np.uint64)
            r = self._rep_cap
            for i, row in enumerate(rows):
                for col, v in self._pending_p.get(row, {}).items():
                    stacked[i, col] = v
                for col, v in self._pending_n.get(row, {}).items():
                    stacked[i, r + col] = v
            lr, d_hi, d_lo, slots = route_drain(
                np.asarray(rows, np.int64),
                stacked,
                self._n_shards,
                self._key_cap // self._n_shards,
            )
            p_hi, p_lo, n_hi, n_lo, sums = drain_sharded_pn(
                self._mesh, *self._state, lr, d_hi, d_lo
            )
            self._state = pncount.PNCountState(p_hi, p_lo, n_hi, n_lo)
            sums = np.asarray(sums)
            for j, g in enumerate(slots):
                if g >= 0:
                    self._values[int(g)] = int(sums[j])
        elif len(rows) * DENSE_FRACTION >= self._key_cap:
            dp = np.zeros((self._key_cap, self._rep_cap), np.uint64)
            dn = np.zeros((self._key_cap, self._rep_cap), np.uint64)
            for row in rows:
                for col, v in self._pending_p.get(row, {}).items():
                    dp[row, col] = v
                for col, v in self._pending_n.get(row, {}).items():
                    dn[row, col] = v
            dp_hi, dp_lo = planes.split64_np(dp)
            dn_hi, dn_lo = planes.split64_np(dn)
            self._state, sums = _drain_pn_dense(
                self._state, dp_hi, dp_lo, dn_hi, dn_lo
            )
            sums = np.asarray(sums)
            for row in rows:
                self._values[row] = int(sums[row])
        else:
            b = bucket(len(rows))
            ki = pad_rows(b)
            ki[: len(rows)] = rows
            dp = np.zeros((b, self._rep_cap), np.uint64)
            dn = np.zeros((b, self._rep_cap), np.uint64)
            for i, row in enumerate(rows):
                for col, v in self._pending_p.get(row, {}).items():
                    dp[i, col] = v
                for col, v in self._pending_n.get(row, {}).items():
                    dn[i, col] = v
            dp_hi, dp_lo = planes.split64_np(dp)
            dn_hi, dn_lo = planes.split64_np(dn)
            self._state, sums = _drain_pn(
                self._state, ki, dp_hi, dp_lo, dn_hi, dn_lo
            )
            sums = np.asarray(sums)
            for i, row in enumerate(rows):
                self._values[row] = int(sums[i])
        self._pending_p.clear()
        self._pending_n.clear()
        self._foreign.clear()

    def flush_deltas(self):
        out = []
        for k in sorted(self._dirty):
            dp = {self._identity: self._own_p[k]} if k in self._own_p else {}
            dn = {self._identity: self._own_n[k]} if k in self._own_n else {}
            out.append((k, (dp, dn)))
        self._dirty.clear()
        return out

    # -- snapshot (persist.py): full state in the wire-delta shape ----------

    def dump_state(self):
        self.drain()
        cols = {col: rid for rid, col in self._rids.items()}
        p = planes.combine64_np(
            np.asarray(self._state.p_hi), np.asarray(self._state.p_lo)
        )
        n = planes.combine64_np(
            np.asarray(self._state.n_hi), np.asarray(self._state.n_lo)
        )
        out = []
        for key, row in sorted(self._keys.items()):
            dp = {cols[c]: int(v) for c, v in enumerate(p[row, : len(cols)]) if v}
            dn = {cols[c]: int(v) for c, v in enumerate(n[row, : len(cols)]) if v}
            if dp or dn:
                out.append((key, (dp, dn)))
        return out

    def load_state(self, batch) -> None:
        for key, (dp, dn) in batch:
            self.converge(key, (dp, dn))
            if self._identity in dp:
                self._own_p[key] = max(
                    self._own_p.get(key, 0), dp[self._identity]
                )
            if self._identity in dn:
                self._own_n[key] = max(
                    self._own_n.get(key, 0), dn[self._identity]
                )
