"""Overload armor: per-class priority admission and load shedding.

The ``--admission-cap`` seed (models/manager.py) bounds one data type's
repo-lock queue — useful against a single hot key, useless against the
node-wide failure mode: offered load above serving capacity. This
module is the node-wide layer: every Python-path command is classified
into one of four priority classes (control > reads > writes > bulk by
default, reorderable via ``--admission-policy``), and when the node
declares itself OVERLOADED — a hysteresis state driven by the dispatch
latency EWMA and the in-flight queue depth — the low-priority classes
are refused up front with a typed BUSY reply carrying a retry-after
hint, before they cost a session flush, a repo lock, or a device
drain. The delta-CRDT discipline (arXiv:1410.2803) keeps replication
cheap under pressure only if serving queues are bounded; Big(ger) Sets
(arXiv:1605.06424) argues the shedding unit must be the smallest one —
per command class, not per connection — which is exactly what the
classifier provides.

Three design points worth naming:

* **SESSION unwrapping.** ``SESSION WRAP <cmd>`` / ``SESSION READ
  <token> <cmd>`` classify as their INNER command, not as SESSION —
  otherwise control-plane priority becomes a write-smuggling channel
  past shedding (the ``--admission-cap`` seed classified by first word
  only; tests/test_admission.py pins the inheritance).
* **Hysteresis, declared.** Overload is a STATE the node enters and
  exits (``serving.overload_enter``/``exit`` trace events, the
  ``serving.overload`` gauge, an OVERLOAD section in SYSTEM METRICS),
  not a per-command coin flip: entry takes ``enter_streak`` consecutive
  pressure observations, exit takes ``exit_streak`` consecutive calm
  ones against a threshold at half the entry latency — so the state
  can't flap per command, and operators/drills can assert transitions.
* **A hard queued-bytes bound.** Reply bytes parked on slow consumers
  (transport write buffers + the per-connection reply buffer) are
  tracked per connection; past ``--admission-queue-bytes`` EVERY class
  is refused, so a slow-consumer burst can never OOM the loop. The
  server additionally caps each connection's transport buffer so
  ``drain()`` applies real per-connection backpressure.

Unarmed cost: with no ``--admission-policy`` and the byte bound idle,
``admit()`` is two attribute reads and an integer compare per command.
The ``admission.shed`` failpoint (drills) forces the shed decision for
sheddable classes without real overload.
"""

from __future__ import annotations

import time

from . import faults

# The four priority classes, most- to least-protected in the DEFAULT
# policy order. Class names are lowercase on the wire (BUSY replies,
# OVERLOAD metrics lines) and in the policy flag.
CONTROL = "control"
READ = "read"
WRITE = "write"
BULK = "bulk"
CLASSES = (CONTROL, READ, WRITE, BULK)

DEFAULT_ORDER = "control>read>write>bulk"

# Read-shaped second words across the data-type repos (repo_*.py).
# Anything else on a known data type is a write unless listed as bulk.
_READ_OPS = frozenset((b"GET", b"SIZE", b"CUTOFF", b"KEYS"))

# Bulk = commands that carry large payloads or trigger whole-structure
# device work; they shed first under the default policy.
_BULK_OPS = frozenset(
    (
        (b"TENSOR", b"SET"),
        (b"TENSOR", b"MRG"),
        (b"UJSON", b"SET"),
        (b"UJSON", b"INS"),
        (b"TLOG", b"TRIM"),
        (b"TLOG", b"TRIMAT"),
    )
)


def classify(cmd: list[bytes]) -> str:
    """The priority class of one parsed command.

    SESSION WRAP / SESSION READ unwrap to the INNER command's class —
    the satellite fix this round pins: wrapping a write in control-plane
    syntax must not promote it past shedding. Bare SESSION ops (TOKEN,
    help) and the SYSTEM family are control. Unknown first words class
    as reads: their reply is a cheap help render, and refusing them
    under overload would hide the help text exactly when an operator is
    debugging."""
    for _ in range(4):  # tolerate (malformed) nested wrapping, bounded
        if not cmd:
            return READ
        first = cmd[0]
        if first == b"SYSTEM":
            return CONTROL
        if first != b"SESSION":
            break
        op = cmd[1] if len(cmd) > 1 else b""
        if op == b"WRAP" and len(cmd) > 2:
            cmd = cmd[2:]
            continue
        if op == b"READ" and len(cmd) > 3:
            cmd = cmd[3:]
            continue
        return CONTROL  # TOKEN / help: genuinely control-plane
    op = cmd[1] if len(cmd) > 1 else b""
    if (first, op) in _BULK_OPS:
        return BULK
    if not op or op in _READ_OPS:
        return READ  # a bare first word is a help render: cheap
    return WRITE


class PolicySpecError(ValueError):
    """Malformed ``--admission-policy`` spec."""


def parse_policy(spec: str) -> dict:
    """``--admission-policy`` syntax::

        control>read>write>bulk[,lat=<enter ms>][,depth=<hi>][,protect=<n>]

    The ``>`` chain is the priority order (must name all four classes
    exactly once); ``lat`` is the dispatch-latency EWMA that declares
    pressure (exit threshold is half of it), ``depth`` the in-flight
    queue depth that declares pressure, ``protect`` how many top ranks
    are NEVER shed while overloaded (default 2: control + the next
    rank). Empty spec = admission disabled (the queued-bytes bound
    still applies)."""
    out = {
        "enabled": bool(spec),
        "order": CLASSES,
        "enter_ms": 25.0,
        "depth_hi": 128,
        "protect": 2,
    }
    if not spec:
        return out
    parts = [p.strip() for p in spec.split(",") if p.strip()]
    order = tuple(c.strip().lower() for c in parts[0].split(">"))
    if sorted(order) != sorted(CLASSES):
        raise PolicySpecError(
            f"policy order must name all of {'/'.join(CLASSES)} exactly "
            f"once: {parts[0]!r}"
        )
    out["order"] = order
    for opt in parts[1:]:
        if "=" not in opt:
            raise PolicySpecError(f"policy option {opt!r} lacks '=value'")
        key, val = opt.split("=", 1)
        try:
            if key == "lat":
                out["enter_ms"] = float(val)
            elif key == "depth":
                out["depth_hi"] = int(val)
            elif key == "protect":
                out["protect"] = int(val)
            else:
                raise PolicySpecError(f"unknown policy option {key!r}")
        except ValueError:
            raise PolicySpecError(
                f"bad value in policy option {opt!r}"
            ) from None
    if not 1 <= out["protect"] < len(CLASSES):
        raise PolicySpecError("protect must be in 1..3")
    return out


# Hysteresis shape: entry is fast (a streak of consecutive pressure
# observations), exit is slow (a longer calm streak against the halved
# threshold) — asymmetry is what keeps the declared state from
# flapping per command at the capacity boundary.
EWMA_ALPHA = 0.05
ENTER_STREAK = 8
EXIT_STREAK = 64
# While overloaded, an EWMA past SEVERE_FACTOR x enter_ms escalates
# shedding from the bottom rank alone to every rank below the protect
# floor (default: bulk first, then writes too) — graceful degradation
# in two steps, with the protected ranks never shed by state.
SEVERE_FACTOR = 4.0
# The EWMA estimates time-in-our-own-queue; a queue does not survive an
# idle gap. Without this reset the state machine can FREEZE overloaded:
# refusals never call done(), so a node that shed its way to (near)
# zero admitted traffic keeps an EWMA stuck at its panic value and the
# exit streak can never complete — the first samples after a lull must
# start the estimate fresh, not average against stale panic.
IDLE_RESET_S = 1.0
# De-escalation (severe -> mild, overloaded -> calm) additionally
# requires this long with NO shed events. Shedding is what makes an
# overloaded node comfortable again — the latency signal collapses the
# moment the floor engages — so a purely latency-driven exit flaps at
# the shed boundary: exit, re-admit the flood, spike the protected
# tail, re-enter. Refusals still happening are direct evidence the
# pressure source is still offering load; only once clients actually
# back off (the BUSY retry-after contract) does the quiet window
# elapse and the calm streak start counting.
EXIT_SHED_QUIET_S = 1.0

_HINT_MIN_MS = 25
_HINT_MAX_MS = 1000


def busy_reply(cls: str, hint_ms: int, why: str) -> str:
    """The typed BUSY refusal body. Clients key on the leading BUSY and
    the machine-readable ``retry-after-ms=`` field (client.py parses
    it); the rest is operator-facing."""
    return (
        f"BUSY (overload shed class={cls} retry-after-ms={hint_ms}; "
        f"{why} — back off and retry)"
    )


class AdmissionController:
    """Node-wide admission state: one per Database, consulted by the
    Server at every Python-path dispatch. Single-threaded (event loop
    only) — no locks."""

    def __init__(self, policy: str = "", queue_bytes: int = 0, registry=None):
        p = parse_policy(policy)
        self.enabled = p["enabled"]
        self.order = p["order"]
        self.enter_ms = p["enter_ms"]
        self.exit_ms = p["enter_ms"] / 2.0
        self.depth_hi = p["depth_hi"]
        self.protect = p["protect"]
        self.queue_bytes_cap = queue_bytes
        self._reg = registry
        self._rank = {cls: i for i, cls in enumerate(self.order)}
        self.overloaded = False
        self.severe = False  # sticky escalation latch (see _shed_floor)
        self._hot = 0  # consecutive pressure observations (calm state)
        self._cool = 0  # consecutive calm observations (overload state)
        self.ewma_ms = 0.0
        self._ewma_init = False
        self._last_done = 0.0
        self._last_shed = 0.0
        self.inflight = 0
        self.shed: dict[str, int] = dict.fromkeys(CLASSES, 0)
        self.enters = 0
        self.exits = 0
        self.queued_bytes = 0
        self._conn_q: dict[int, int] = {}

    # ---- the admit decision (hot path) ------------------------------------

    @property
    def armed(self) -> bool:
        """Whether the server should classify at all: policy on, or the
        byte bound configured. False = zero per-command work."""
        return self.enabled or self.queue_bytes_cap > 0

    def _hint_ms(self, rank: int) -> int:
        base = max(self.ewma_ms * 2.0, float(_HINT_MIN_MS))
        return min(int(base * (1 + rank)), _HINT_MAX_MS)

    def _shed_floor(self) -> int:
        """Lowest rank that still gets served while overloaded. Ranks at
        or past the floor shed; the floor never drops below ``protect``
        (those ranks are the contract the bench's protected-class p99.9
        is measured against), and escalates one step tighter — toward
        protect, not past it — when the EWMA says severe. The
        escalation is a STICKY latch: it engages at SEVERE_FACTOR x
        enter_ms but only releases once the EWMA is back DOWN to
        enter_ms AND no shed fired for EXIT_SHED_QUIET_S — releasing at
        the engage threshold (or while refusals were still streaming)
        made the floor oscillate (shed -> queue drains -> re-admit ->
        queue spikes) and each re-admit spike landed on the protected
        class's tail."""
        if self.ewma_ms >= self.enter_ms * SEVERE_FACTOR:
            self.severe = True
        elif (
            self.ewma_ms <= self.enter_ms
            and time.perf_counter() - self._last_shed >= EXIT_SHED_QUIET_S
        ):
            self.severe = False
        floor = self.protect if self.severe else len(self.order) - 1
        return max(min(floor, len(self.order) - 1), self.protect)

    def admit(self, cls: str, forced: bool = False) -> int | None:
        """None = admitted (caller MUST pair with done()); an int is the
        retry-after hint in ms for a typed BUSY refusal. ``forced`` is
        the armed ``admission.shed`` failpoint: shed every sheddable
        (non-control) class regardless of state — the deterministic
        drill lever."""
        rank = self._rank.get(cls, len(self.order) - 1)
        if (
            self.queue_bytes_cap
            and self.queued_bytes > self.queue_bytes_cap
        ):
            # the hard bound outranks priority: admitting ANY class
            # grows reply bytes the consumers are not draining
            return self._refuse(cls, rank)
        if forced and rank > 0:
            return self._refuse(cls, rank)
        if self.enabled and self.overloaded and rank >= self._shed_floor():
            return self._refuse(cls, rank)
        self.inflight += 1
        return None

    def _refuse(self, cls: str, rank: int) -> int:
        self.shed[cls] += 1
        # every refusal restarts the de-escalation quiet window: see
        # EXIT_SHED_QUIET_S — refusals ARE the ongoing-pressure signal
        self._last_shed = time.perf_counter()
        return self._hint_ms(rank)

    def done(self, cls: str, seconds: float) -> None:
        """Completion of an admitted dispatch: feeds the latency EWMA
        and steps the hysteresis state machine. ``seconds`` <= 0 means
        the caller had timing disabled — the depth signal still runs."""
        if self.inflight > 0:
            self.inflight -= 1
        if seconds > 0.0:
            ms = seconds * 1e3
            now = time.perf_counter()
            stale = now - self._last_done > IDLE_RESET_S
            self._last_done = now
            if not self._ewma_init or stale:
                self.ewma_ms = ms
                self._ewma_init = True
            else:
                self.ewma_ms += EWMA_ALPHA * (ms - self.ewma_ms)
        if not self.enabled:
            return
        pressure = (
            self.ewma_ms >= self.enter_ms or self.inflight >= self.depth_hi
        )
        if not self.overloaded:
            self._hot = self._hot + 1 if pressure else 0
            if self._hot >= ENTER_STREAK:
                self._enter()
        else:
            calm = (
                self.ewma_ms <= self.exit_ms
                and self.inflight < self.depth_hi
                and time.perf_counter() - self._last_shed >= EXIT_SHED_QUIET_S
            )
            self._cool = self._cool + 1 if calm else 0
            if self._cool >= EXIT_STREAK:
                self._exit()

    def _enter(self) -> None:
        self.overloaded = True
        self.enters += 1
        self._hot = 0
        self._cool = 0
        if self._reg is not None:
            self._reg.gauge_set("serving.overload", 1.0)
            self._reg.trace_event(
                "serving", "overload_enter", "",
                f"ewma_ms={self.ewma_ms:.1f} inflight={self.inflight}",
            )

    def _exit(self) -> None:
        self.overloaded = False
        self.severe = False
        self.exits += 1
        self._hot = 0
        self._cool = 0
        if self._reg is not None:
            self._reg.gauge_set("serving.overload", 0.0)
            self._reg.trace_event(
                "serving", "overload_exit", "",
                f"ewma_ms={self.ewma_ms:.1f} shed={sum(self.shed.values())}",
            )

    # ---- queued-bytes accounting (slow-consumer OOM bound) ----------------

    def note_conn_queued(self, conn_id: int, nbytes: int) -> None:
        """Current un-drained reply bytes for one connection (transport
        write buffer + the server's per-connection reply buffer);
        maintained incrementally so the total is O(1) per update."""
        prev = self._conn_q.get(conn_id, 0)
        if nbytes != prev:
            self._conn_q[conn_id] = nbytes
            self.queued_bytes += nbytes - prev
            if self._reg is not None and self._reg.enabled:
                self._reg.gauge_set(
                    "serving.queued_bytes", float(self.queued_bytes)
                )

    def drop_conn(self, conn_id: int) -> None:
        self.note_conn_queued(conn_id, 0)
        self._conn_q.pop(conn_id, None)

    # ---- reporting (OVERLOAD section of SYSTEM METRICS, prom.py) ----------

    def metrics_totals(self) -> dict[str, int]:
        """Glossary order, stable for dashboards (docs/operations.md):
        the declared state first, then transitions, then per-class shed
        counters, then the live signals."""
        out = {
            "armed": 1 if self.armed else 0,
            "state": 1 if self.overloaded else 0,
            "enters": self.enters,
            "exits": self.exits,
        }
        for cls in CLASSES:
            out[f"shed_{cls}"] = self.shed[cls]
        out["ewma_us"] = int(self.ewma_ms * 1e3)
        out["inflight"] = self.inflight
        out["queued_bytes"] = self.queued_bytes
        return out


async def gate(adm: AdmissionController, cls: str) -> int | None:
    """The server's per-dispatch admission consult: the async fault
    seam (``admission.shed`` — drills force shedding without real
    overload; async so an injected sleep stalls only this connection,
    the JL101 lesson from native.scan_apply) wrapped around the sync
    decision. None = admitted, else the retry-after hint in ms."""
    forced = False
    try:
        await faults.async_point("admission.shed")
    except faults.FaultError:
        forced = True
    return adm.admit(cls, forced=forced)
