"""Heartbeat timer driving the cluster's periodic work.

Reference analog: heart.pony:6-19 — a timer firing ``target._heartbeat()``
every ``heartbeat_time`` seconds (default 10 s, config.pony:9). Here the
Pony timer becomes an asyncio task; the target contract stays the same
(anything with a ``_heartbeat()`` method, _HeartbeatableActor analog).
"""

from __future__ import annotations

import asyncio


class Heart:
    def __init__(self, target, interval_s: float):
        self._target = target
        self._interval = interval_s
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        try:
            while True:
                await asyncio.sleep(self._interval)
                try:
                    self._target._heartbeat()
                except Exception as e:  # noqa: BLE001  jlint: broad-ok
                    # a transient tick failure must not kill the heart: a
                    # dead heart means no dialing, no eviction, and no
                    # anti-entropy while the node keeps serving clients
                    log = getattr(self._target, "_log", None)
                    if log is not None:
                        log.err() and log.e(f"heartbeat tick failed: {e!r}")
        except asyncio.CancelledError:
            pass

    def dispose(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
