"""Cluster protocol messages.

Reference analog: msg.pony:3-24 — four message kinds cross the cluster
wire: ``MsgPong`` (liveness ack), ``MsgExchangeAddrs`` (full membership
sync: carries the sender's whole P2Set, receiver converges and replies in
kind), ``MsgAnnounceAddrs`` (periodic membership gossip: receiver converges
and replies Pong), and ``MsgPushDeltas`` (anti-entropy: one data type's
drained delta batch).

The reference serialises these with the Pony runtime's whole-object-graph
``Serialise`` (_serialise.pony:3-14); here each message has an explicit
versioned binary encoding (codec.py) with a schema signature replacing the
reference's "same binary" handshake digest.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..ops.p2set import P2Set
from ..utils.address import Address


@dataclass(frozen=True)
class MsgPong:
    pass


@dataclass(frozen=True)
class MsgSyncDone:
    """Reply closing a MsgSyncRequest: sent after the dump stream (or
    instead of one, when the request is deferred / digest-matched /
    rate-limited). Distinct from MsgPong so the requester's heartbeat
    round-trip histogram stays exact: every Pong the active side
    receives then answers a stamped push/announce send in FIFO order,
    and sync replies — whose timing includes digest computation or a
    whole dump stream — never consume a round-trip stamp."""


@dataclass(frozen=True)
class MsgExchangeAddrs:
    known_addrs: P2Set  # P2Set[Address]


@dataclass(frozen=True)
class MsgAnnounceAddrs:
    known_addrs: P2Set  # P2Set[Address]


@dataclass(frozen=True)
class MsgPushDeltas:
    """(data-type name, [(key, delta)]) — the _SendDeltasFn payload shape
    (_send_deltas_fn.pony:1-2)."""

    name: str
    batch: tuple  # tuple[(key: bytes, delta), ...]


@dataclass(frozen=True)
class MsgSyncRequest:
    """Bootstrap/rejoin full-state sync (beyond the reference, which can
    permanently miss deltas flushed while a peer was away —
    cluster.pony:250-252 converges only what is pushed). The requester
    sends this after establishing an active connection (and periodically
    thereafter) WITH its own PER-TYPE data-state digests; a peer whose
    digests all match replies MsgSyncDone (the requester is already in sync
    — a flapping connection re-ships nothing), otherwise it streams ONLY
    the mismatched types' state as chunked MsgPushDeltas batches (the
    snapshot wire shape, persist.py), which converge idempotently.

    digests: one 32-byte incremental digest per DATA type, in
    Database.DATA_TYPES order (TREG, TLOG, GCOUNT, PNCOUNT, UJSON,
    TENSOR —
    SYSTEM excluded: its log advances on connection events themselves,
    which would make two in-sync peers never match). Each is the XOR of
    sha256(canonical per-key state) over the type's keys."""

    digests: tuple = ()


Msg = (
    MsgPong
    | MsgSyncDone
    | MsgExchangeAddrs
    | MsgAnnounceAddrs
    | MsgPushDeltas
    | MsgSyncRequest
)
