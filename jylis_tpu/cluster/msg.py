"""Cluster protocol messages.

Reference analog: msg.pony:3-24 — four message kinds cross the cluster
wire: ``MsgPong`` (liveness ack), ``MsgExchangeAddrs`` (full membership
sync: carries the sender's whole P2Set, receiver converges and replies in
kind), ``MsgAnnounceAddrs`` (periodic membership gossip: receiver converges
and replies Pong), and ``MsgPushDeltas`` (anti-entropy: one data type's
drained delta batch).

The reference serialises these with the Pony runtime's whole-object-graph
``Serialise`` (_serialise.pony:3-14); here each message has an explicit
versioned binary encoding (codec.py) with a schema signature replacing the
reference's "same binary" handshake digest.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..ops.p2set import P2Set
from ..utils.address import Address


@dataclass(frozen=True)
class MsgPong:
    pass


@dataclass(frozen=True)
class MsgSyncDone:
    """Reply closing a MsgSyncRequest: sent after the dump stream (or
    instead of one, when the request is deferred / digest-matched /
    rate-limited). Distinct from MsgPong so the requester's heartbeat
    round-trip histogram stays exact: every Pong the active side
    receives then answers a stamped push/announce send in FIFO order,
    and sync replies — whose timing includes digest computation or a
    whole dump stream — never consume a round-trip stamp.

    Schema v10: carries the responder's session vector — NON-EMPTY ONLY
    on the digest-match branch, where byte-equal state proves every
    write the responder's vector covers is in the requester's state too
    (the adoption rule sessions.py relies on; any other branch sends it
    empty). This is how a fresh joiner's session index bootstraps and
    how a rebooted origin re-learns its own pre-crash watermark."""

    svec: tuple = ()  # tuple[(rid: str, seq: int), ...]


@dataclass(frozen=True)
class MsgExchangeAddrs:
    known_addrs: P2Set  # P2Set[Address]


@dataclass(frozen=True)
class MsgAnnounceAddrs:
    known_addrs: P2Set  # P2Set[Address]


@dataclass(frozen=True)
class MsgPushDeltas:
    """(data-type name, [(key, delta)]) — the _SendDeltasFn payload shape
    (_send_deltas_fn.pony:1-2)."""

    name: str
    batch: tuple  # tuple[(key: bytes, delta), ...]


@dataclass(frozen=True)
class MsgSeqPush:
    """Schema v8 delta-interval broadcast: a MsgPushDeltas payload
    stamped with the SENDER's per-sender monotone batch sequence. The
    receiver tracks the highest contiguous seq per sender and answers
    every SeqPush with MsgDeltaAck(cum) — the sender retransmits only
    the unacked window on reconnection, so a short blip reships exactly
    the missed batches instead of falling through to a state sync
    ("Efficient State-based CRDTs by Delta-Mutation", arXiv:1410.2803's
    delta-interval algorithm). Content-free keepalives (the SYSTEM
    deltas_size()==1 quirk) stay unsequenced MsgPushDeltas: sequencing
    them would burn retransmit-window slots on frames that carry
    nothing.

    Schema v10: also carries ``oseq``, the sender's OWN-CONTENT ordinal
    — a second counter that ticks only for the sender's own batches,
    never for the relay frames a bridge interleaves into its transport
    stream. Session vectors (sessions.py) track oseq, not seq: oseq is
    gapless per origin, so the same contiguity rule works at direct
    receivers AND transitively through any number of relay hops, where
    the intermediate bridges' transport-seq consumption is invisible.
    The transport machinery (acks, retransmit, _recv_cum) stays on
    ``seq``.

    Schema v11: also carries ``span``, a sampled provenance trace
    (obs/jtrace.py — empty for the 1-in-N complement, one length byte
    on the wire). Transport-only like oseq: the delta signature is
    untouched. Declared LAST with a default so every positional
    construction (and the golden corpus) predating v11 stays valid."""

    seq: int
    oseq: int
    name: str
    batch: tuple  # tuple[(key: bytes, delta), ...]
    span: bytes = b""


@dataclass(frozen=True)
class MsgDeltaAck:
    """Cumulative contiguous ack of a sender's MsgSeqPush stream: "I
    have applied every batch of yours up to and including cum". Sent by
    the receiver for EVERY SeqPush (duplicates included — the ack
    re-states cum), it doubles as the push path's liveness reply, so it
    consumes the sender's rtt stamp exactly like a Pong."""

    cum: int


@dataclass(frozen=True)
class MsgDigestTree:
    """One type's keyspace-range digest tree (schema v8 Merkle-range
    repair, after "Big(ger) Sets", arXiv:1605.06424): sparse non-empty
    leaves of the 256-bucket tree over sha256(key)[0], each leaf the
    XOR of its keys' canonical per-key state hashes. Sent by a sync
    responder for each type whose ROOT digest mismatches the
    requester's — ~8 KB instead of a keyspace dump; the requester
    compares leaves and pulls only divergent buckets via
    MsgRangeRequest. An EMPTY tree (zero leaves) is legal: it means the
    responder holds no keys of that type."""

    name: str
    leaves: tuple = ()  # tuple[(bucket: int, digest: bytes32), ...]


@dataclass(frozen=True)
class MsgRangeRequest:
    """Pull one type's state for the named digest-tree buckets only.
    The responder streams the range as chunked MsgPushDeltas frames
    (the snapshot wire shape — converges idempotently) and closes with
    MsgSyncDone; the requester walks remaining divergent buckets in
    budgeted rounds, so repair bytes AND repair work scale with
    divergence, never with keyspace. An empty bucket list is legal and
    serves nothing but the SyncDone."""

    name: str
    buckets: tuple = ()  # tuple[int, ...]


@dataclass(frozen=True)
class MsgIntervalReset:
    """The sender's delta log can no longer replay this receiver's gap
    (held past the retransmit window, or evicted at the cap mid-
    partition): "re-baseline your contiguity cursor to seq and pull a
    range repair from me". The graceful-degradation rung between
    interval retransmit and range repair — the receiver clears its
    out-of-order set, adopts seq, and forces a digest-tree sync toward
    the sender, so held-window loss demotes to range repair instead of
    silent divergence (or a whole-state dump)."""

    seq: int


@dataclass(frozen=True)
class MsgSyncRequest:
    """Bootstrap/rejoin full-state sync (beyond the reference, which can
    permanently miss deltas flushed while a peer was away —
    cluster.pony:250-252 converges only what is pushed). The requester
    sends this after establishing an active connection (and periodically
    thereafter) WITH its own PER-TYPE data-state digests; a peer whose
    digests all match replies MsgSyncDone (the requester is already in sync
    — a flapping connection re-ships nothing), otherwise it streams ONLY
    the mismatched types' state as chunked MsgPushDeltas batches (the
    snapshot wire shape, persist.py), which converge idempotently.

    digests: one 32-byte incremental digest per DATA type, in
    Database.DATA_TYPES order (TREG, TLOG, GCOUNT, PNCOUNT, UJSON,
    TENSOR, MAP, BCOUNT — models/database.py DATA_REPO_CLASSES —
    SYSTEM excluded: its log advances on connection events themselves,
    which would make two in-sync peers never match). Each is the XOR of
    sha256(canonical per-key state) over the type's keys.

    Schema v10: also carries the requester's session vector, snapshotted
    BEFORE its digests were computed (so the vector never claims more
    than the digested state holds). On a digest match the responder
    adopts it — the symmetric half of MsgSyncDone's svec."""

    digests: tuple = ()
    svec: tuple = ()  # tuple[(rid: str, seq: int), ...]


@dataclass(frozen=True)
class MsgRelayPush:
    """Schema v10 origin-preserving relay: a MsgSeqPush whose content
    ORIGINATED at another replica, re-exported by a bridge (a region
    bridge between WAN meshes, or lane 0 between the lane bus and the
    external mesh). ``seq`` is the RELAYING sender's transport seq —
    the frame rides its delta log, is acked by MsgDeltaAck and
    retransmitted on reconnect exactly like a SeqPush, so transport
    contiguity per sender is preserved even though bridges fan subsets
    of traffic. ``origin``/``oseq`` are the originating incarnation's
    rid (sessions.make_rid) and ITS batch seq, carried verbatim hop to
    hop: receivers advance their session vector for the ORIGIN, which
    is what lets a session token minted in one region verify in
    another. name+batch bytes are msg3's after the prefix (native codec
    fast path serves the relay hot path too).

    Schema v11: carries ``span`` like MsgSeqPush — the relaying bridge
    appends its own hop stamp to the origin's chain before re-export,
    which is what makes the WAN leg visible in SYSTEM TRACE SPANS."""

    seq: int
    origin: str
    oseq: int
    name: str
    batch: tuple  # tuple[(key: bytes, delta), ...]
    span: bytes = b""


@dataclass(frozen=True)
class MsgRegionGossip:
    """Region membership gossip (schema v10): (advertised address,
    region name, epoch) triples, broadcast on the announce cadence.
    Regions also ride the handshake; the gossip is what lets a node
    classify addresses it has never dialed (the region-aware peering
    policy needs every KNOWN address's region to pick the
    deterministic bridge and prune out-of-region dials). Each entry is
    VERSIONED by the subject node's boot epoch and folds
    highest-epoch-wins — unversioned gossip would let stale maps
    oscillate the cluster's classification (and so bridge election)
    forever after a node's region changes across a restart."""

    regions: tuple = ()  # tuple[(addr: str, region: str, epoch: int), ...]


Msg = (
    MsgPong
    | MsgSyncDone
    | MsgExchangeAddrs
    | MsgAnnounceAddrs
    | MsgPushDeltas
    | MsgSyncRequest
    | MsgSeqPush
    | MsgDeltaAck
    | MsgDigestTree
    | MsgRangeRequest
    | MsgIntervalReset
    | MsgRelayPush
    | MsgRegionGossip
)
