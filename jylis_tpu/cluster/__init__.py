"""Cluster layer: gossip membership + anti-entropy replication (L5).

Reference analog: cluster.pony + heart.pony + msg.pony + framing.pony +
framed_notify.pony + cluster_notify.pony + _serialise.pony (SURVEY.md §2.5).
"""

from .cluster import Cluster
from .heart import Heart
from .msg import MsgAnnounceAddrs, MsgExchangeAddrs, MsgPong, MsgPushDeltas

__all__ = [
    "Cluster",
    "Heart",
    "MsgPong",
    "MsgExchangeAddrs",
    "MsgAnnounceAddrs",
    "MsgPushDeltas",
]
