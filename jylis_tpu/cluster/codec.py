"""Versioned binary codec for cluster messages + schema signature.

Reference analog: _serialise.pony:3-14. The reference ships whole Pony
object graphs with the runtime serialiser and guards compatibility with a
build-identity digest — "both peers must run the same binary". That is
replaced here by the design SURVEY.md §5.8 calls for: an explicit schema
with a versioned signature, so any two builds speaking the same *schema*
interoperate. The handshake (cluster_notify.pony:37-61 analog) exchanges
``signature()`` as the first frame; a byte mismatch drops the connection.

Encoding: LEB128 varints for all integers, varint-length-prefixed byte
strings, and a one-byte tag per message / per delta kind. Delta payloads
are encoded per data type (the wire shapes documented in each repo module):

    TREG           (value: bytes, ts: u64)
    TLOG / SYSTEM  ([(value: bytes, ts: u64)...], cutoff: u64)
    GCOUNT         {replica-id: u64}
    PNCOUNT        ({rid: u64}, {rid: u64})
    UJSON          dot-store entries + causal context (ops/ujson_host.py)
    TENSOR         uniform 4-plane unit + AVG contribs (ops/tensor_host.py)
    MAP            one FIELD unit (itype, ver, tomb, inner delta) under a
                   packed (key, field) wire key — recursive (ops/compose.py)
    BCOUNT         full escrow view (grants, incs, decs, xi, xd)
                   (ops/bcount.py)

A native C++ fast path for the MsgPushDeltas hot loop (the per-key delta
packing on every anti-entropy broadcast/converge) lives in
native/cluster_codec.cpp behind jylis_tpu/native/codec.py; encode()/
decode() below try it first and fall back here — for every data type,
UJSON included. This module is the always-available implementation and
the byte-level correctness oracle (fuzz-differential tests:
tests/test_native_codec.py); only membership messages always take this
path.
"""

from __future__ import annotations

import hashlib

from ..ops import compose
from ..ops.p2set import P2Set
from ..ops.tensor_host import Tensor
from ..ops.ujson_host import UJSON
from ..ops.ujson_wire import read_ujson
from ..utils.address import Address
from ..utils.wire import Reader as _Reader
from ..utils.wire import WireError
from .msg import (
    Msg,
    MsgAnnounceAddrs,
    MsgDeltaAck,
    MsgDigestTree,
    MsgExchangeAddrs,
    MsgIntervalReset,
    MsgPong,
    MsgPushDeltas,
    MsgRangeRequest,
    MsgRegionGossip,
    MsgRelayPush,
    MsgSeqPush,
    MsgSyncDone,
    MsgSyncRequest,
)

SCHEMA_VERSION = 11

# The canonical schema text: any change to the wire format MUST change this
# string (bump SCHEMA_VERSION), which changes the signature, which makes
# incompatible peers refuse each other at handshake instead of corrupting.
# v5: (a) every transport frame body is prefixed with its CRC32 —
# without it a single bit flip past the TCP checksum can decode as a
# valid message and converge as forged lattice state (found by the
# drill matrix); (b) the dialer's handshake frame carries its
# advertised address after the 32-byte signature (the passive side uses
# it to identify the peer for teardown logs and to reset its dial
# backoff on inbound contact); the passive echo remains the bare
# signature.
# v6: every transport frame carries its sender's wall-clock origin
# (milliseconds, u64be, CRC-covered) between the CRC and the body —
# mirroring the v5 handshake-address precedent of enriching the
# TRANSPORT layer rather than the message encodings, so snapshots and
# journals (which store bare message payloads versioned by
# delta_signature) remain loadable across the bump. Receivers fold the
# stamp into per-peer convergence-lag gauges (push→apply staleness, the
# quantity a delta-CRDT store exists to bound) and heartbeat round-trip
# histograms; origin 0 means "unstamped" and records nothing. Sync
# replies get their own message (msg5 SyncDone) so a Pong always
# answers a round-trip-stamped send and the rtt histogram's FIFO
# matching stays exact — a sync reply's timing includes digest
# computation or a whole dump stream, which is not a round trip.
# v7: the TENSOR data type (ops/tensor_host.py — fixed-dim f32 vectors
# with per-coordinate MAX / LWW / timestamp-weighted-AVG joins). One
# uniform delta shape for all three merge modes: every plane ships
# every time (empty bytes for the planes a mode does not use), so the
# encoder/decoder bodies stay branch-free for pass 7's symmetry
# extractor. `vec` payloads are packed little-endian f32 with NaNs
# canonicalised at ingest. This is the FIRST delta-line change since
# v1, so delta_signature() changes for the first time: v1-v6 snapshots
# and journals (which stamp the delta signature) stay loadable via the
# legacy acceptance below — they contain only old-type frames, all
# still decodable.
# v8: the anti-entropy rewrite — five new TRANSPORT messages, zero
# delta-line changes (so delta_signature() is UNCHANGED from v7 and
# every v7 snapshot/journal stays first-class loadable; v1-v6 remain
# covered by the legacy acceptance). msg6/msg7 are the delta-interval
# half (per-sender monotone batch seqs, cumulative contiguous acks,
# retransmit-only-unacked — arXiv:1410.2803); msg8/msg9 are the
# Merkle-range half (a 256-leaf keyspace digest tree over
# sha256(key)[0], range pulls of divergent buckets only —
# arXiv:1605.06424); msg10 is the graceful-degradation rung between
# them (a sender whose retransmit window evicted a receiver's gap
# re-baselines that receiver and demotes it to range repair — never a
# silent whole-state dump). msg7's name+batch encoding is byte-
# identical to msg3 after the tag+seq prefix, so the native codec fast
# path serves both.
# v9: the composed types (ROADMAP item 4). Two new delta lines, the
# SECOND delta-line change ever (so delta_signature() changes and the
# v7/v8 delta digest joins the legacy acceptance — those files' frames
# all still decode; v1-v6 remain covered by the older legacy entry).
# delta/MAP is the first RECURSIVE unit: one FIELD of one map key —
# the wire key is the packed (key, field) composite (klen:varint key
# field), the unit is the field's product-lattice state (inner type
# tag, per-replica edit counters, removal tombstone), and `val` is the
# inner type's OWN delta encoding, one level deep (itype must be a
# registered inner lattice: TREG, TLOG, GCOUNT, PNCOUNT — never MAP).
# Decomposition means one field edit ships one unit, never the map,
# and the digest tree / range-repair ladder operates per field.
# delta/BCOUNT is the escrow counter's FULL per-key view (five
# join-monotone components — grants/incs/decs and the two transfer
# matrices); shipping the whole view keeps every state self-justifying
# under join, which is what makes `0 <= value <= bound` hold on every
# replica in every delivery schedule (ops/bcount.py). msg4's digest
# order gains MAP,BCOUNT at the tail (positional vector, transport
# level).
# v10: sessions & regions — transport-only (delta lines unchanged, so
# delta_signature() is UNCHANGED from v9: every existing snapshot and
# journal loads as-is). The dialer's handshake suffix becomes a hello
# (advertised address + region name + boot epoch) and the passive echo
# answers with its own region + epoch: the epoch is what keys session
# vectors per incarnation (a rebooted sender's restarted seq counter
# must never alias its previous stream), the region is what the
# region-aware peering policy classifies conns by. msg4/msg5 gain the
# session vector (svec) for digest-match adoption — byte-equal state is
# the proof that lets a whole vector fold across. msg7 gains the sender's own-content ordinal (oseq — the
# session counter, gapless per origin because relay frames never
# consume it; transport acks stay on seq). msg11 is the
# origin-preserving relay (transport-sequenced like msg7, its name+batch
# bytes msg3's after the prefix, with the ORIGIN incarnation's rid+seq
# carried verbatim hop to hop — how a session token minted in one
# region or lane verifies in another). msg12 gossips {addr -> region}
# on the announce cadence so dial policy can classify addresses it
# never met.
# v11: provenance spans — transport-only like v8/v10 (delta lines
# unchanged, so delta_signature() is UNCHANGED from v9 and every
# snapshot/journal loads as-is). msg7 and msg11 gain ``span``, a
# length-prefixed opaque trace chain (obs/jtrace.py wire format:
# tag/len-framed hop stamps, appended per hop) minted for 1-in-N
# sequenced flushes (--trace-sample) and empty otherwise — the
# unsampled cost is ONE length byte. The span sits in the prefix
# (after oseq, before name) so msg7/msg11's name+batch bytes remain
# msg3's after the prefix and the native codec fast path keeps serving
# both; receivers fold arrived chains into per-hop and per-region-pair
# convergence histograms and the converge_slo gauges. Retransmits
# replay the originally wired bytes, original stamps included.
_SCHEMA_TEXT = f"""jylis-tpu cluster schema v{SCHEMA_VERSION}
varint=LEB128 bytes=varint-len-prefixed str=utf8-bytes
wire=frame(crc32(origin_ms:u64be body):u32be origin_ms:u64be body)
handshake=wire(sig:32B hello:(dialer-addr:addr region:str epoch:varint)?) echo=wire(sig:32B region:str epoch:varint)
addr=(host:str port:str name:str)
p2set=(adds:[addr] removes:[addr])
svec=[(rid:str seq:varint)]
msg0=Pong
msg1=ExchangeAddrs(p2set)
msg2=AnnounceAddrs(p2set)
msg3=PushDeltas(name:str batch:[(key:bytes delta)])
msg4=SyncRequest(digests:[bytes] order=TREG,TLOG,GCOUNT,PNCOUNT,UJSON,TENSOR,MAP,BCOUNT svec)
msg5=SyncDone(svec match-only)
msg6=DeltaAck(cum:varint)
msg7=SeqPush(seq:varint oseq:varint span:bytes name:str batch:[(key:bytes delta)])
msg8=DigestTree(name:str leaves:[(bucket:varint digest:bytes)] fanout=256 bucket=sha256(key)[0])
msg9=RangeRequest(name:str buckets:[varint])
msg10=IntervalReset(seq:varint)
msg11=RelayPush(seq:varint origin:str oseq:varint span:bytes name:str batch:[(key:bytes delta)])
msg12=RegionGossip(regions:[(addr:str region:str epoch:varint)])
delta/TREG=(value:bytes ts:varint)
delta/TLOG=delta/SYSTEM=(entries:[(value:bytes ts:varint)] cutoff:varint)
delta/GCOUNT=[(rid:varint v:varint)]
delta/PNCOUNT=(gcount gcount)
delta/UJSON=(entries:[(rid seq path:[str] token:str)] vv:[(rid seq)] cloud:[(rid seq)])
delta/TENSOR=(mode:varint dim:varint val:bytes ts:bytes rid:bytes contribs:[(rid:varint ts:varint vec:bytes)])
delta/MAP=(itype:str ver:[(rid:varint seq:varint)] tomb:[(rid:varint seq:varint)] val:delta/itype) key=(klen:varint key field) itype in TREG,TLOG,GCOUNT,PNCOUNT
delta/BCOUNT=(grants:[(rid:varint v:varint)] incs:[(rid:varint v:varint)] decs:[(rid:varint v:varint)] xi:[(from:varint to:varint v:varint)] xd:[(from:varint to:varint v:varint)])
"""


def signature() -> bytes:
    """The handshake digest (the reference's _Serialise.signature analog,
    _serialise.pony:7) — here a schema identity, not a binary identity."""
    return hashlib.sha256(_SCHEMA_TEXT.encode()).digest()


def delta_signature() -> bytes:
    """Identity of the PER-TYPE DELTA encodings only (the lines of the
    schema snapshots actually contain). Snapshots are versioned by THIS,
    not the full transport signature: a transport-message change (like
    the v3 sync-request digest) must not invalidate every snapshot on
    disk when the delta bytes it stores are unchanged."""
    delta_lines = [
        line
        for line in _SCHEMA_TEXT.splitlines()
        if line.startswith("delta/") or line.startswith("varint=")
    ]
    return hashlib.sha256("\n".join(delta_lines).encode()).digest()


# the exact schema texts earlier releases stamped into snapshot headers
# via the FULL signature() — their delta lines are byte-identical to
# v3's, so those files remain loadable; kept verbatim (not derived from
# _SCHEMA_TEXT) so future schema edits cannot silently change what a
# legacy header means
_LEGACY_V1_TEXT = """jylis-tpu cluster schema v1
varint=LEB128 bytes=varint-len-prefixed str=utf8-bytes
addr=(host:str port:str name:str)
p2set=(adds:[addr] removes:[addr])
msg0=Pong
msg1=ExchangeAddrs(p2set)
msg2=AnnounceAddrs(p2set)
msg3=PushDeltas(name:str batch:[(key:bytes delta)])
delta/TREG=(value:bytes ts:varint)
delta/TLOG=delta/SYSTEM=(entries:[(value:bytes ts:varint)] cutoff:varint)
delta/GCOUNT=[(rid:varint v:varint)]
delta/PNCOUNT=(gcount gcount)
delta/UJSON=(entries:[(rid seq path:[str] token:str)] vv:[(rid seq)] cloud:[(rid seq)])
"""

_LEGACY_V2_TEXT = """jylis-tpu cluster schema v2
varint=LEB128 bytes=varint-len-prefixed str=utf8-bytes
addr=(host:str port:str name:str)
p2set=(adds:[addr] removes:[addr])
msg0=Pong
msg1=ExchangeAddrs(p2set)
msg2=AnnounceAddrs(p2set)
msg3=PushDeltas(name:str batch:[(key:bytes delta)])
msg4=SyncRequest
delta/TREG=(value:bytes ts:varint)
delta/TLOG=delta/SYSTEM=(entries:[(value:bytes ts:varint)] cutoff:varint)
delta/GCOUNT=[(rid:varint v:varint)]
delta/PNCOUNT=(gcount gcount)
delta/UJSON=(entries:[(rid seq path:[str] token:str)] vv:[(rid seq)] cloud:[(rid seq)])
"""


# the early-v3 window ALSO stamped the full signature() (persist.py
# switched to delta_signature() later in that release cycle); the v3
# text is frozen verbatim like the others so a future schema v4 cannot
# silently change what this header means
_LEGACY_V3_TEXT = """jylis-tpu cluster schema v3
varint=LEB128 bytes=varint-len-prefixed str=utf8-bytes
addr=(host:str port:str name:str)
p2set=(adds:[addr] removes:[addr])
msg0=Pong
msg1=ExchangeAddrs(p2set)
msg2=AnnounceAddrs(p2set)
msg3=PushDeltas(name:str batch:[(key:bytes delta)])
msg4=SyncRequest(digest:bytes)
delta/TREG=(value:bytes ts:varint)
delta/TLOG=delta/SYSTEM=(entries:[(value:bytes ts:varint)] cutoff:varint)
delta/GCOUNT=[(rid:varint v:varint)]
delta/PNCOUNT=(gcount gcount)
delta/UJSON=(entries:[(rid seq path:[str] token:str)] vv:[(rid seq)] cloud:[(rid seq)])
"""


# v4 through v6 stamped delta_signature() into snapshot AND journal
# headers; their delta lines are byte-identical to v1's, so the ONE
# legacy delta digest below covers that whole window. Frozen verbatim
# (not derived from _SCHEMA_TEXT) like the full-signature texts above.
_LEGACY_V6_TEXT = """jylis-tpu cluster schema v6
varint=LEB128 bytes=varint-len-prefixed str=utf8-bytes
wire=frame(crc32(origin_ms:u64be body):u32be origin_ms:u64be body)
handshake=wire(sig:32B dialer-addr:addr?)
addr=(host:str port:str name:str)
p2set=(adds:[addr] removes:[addr])
msg0=Pong
msg1=ExchangeAddrs(p2set)
msg2=AnnounceAddrs(p2set)
msg3=PushDeltas(name:str batch:[(key:bytes delta)])
msg4=SyncRequest(digests:[bytes] order=TREG,TLOG,GCOUNT,PNCOUNT,UJSON)
msg5=SyncDone
delta/TREG=(value:bytes ts:varint)
delta/TLOG=delta/SYSTEM=(entries:[(value:bytes ts:varint)] cutoff:varint)
delta/GCOUNT=[(rid:varint v:varint)]
delta/PNCOUNT=(gcount gcount)
delta/UJSON=(entries:[(rid seq path:[str] token:str)] vv:[(rid seq)] cloud:[(rid seq)])
"""


# the v7/v8 window's schema (v8 touched only transport messages, so
# both releases stamped ONE delta digest: v1-v6's lines plus
# delta/TENSOR). Frozen verbatim like the other legacy texts so future
# schema edits cannot silently change what those on-disk headers mean.
_LEGACY_V8_TEXT = """jylis-tpu cluster schema v8
varint=LEB128 bytes=varint-len-prefixed str=utf8-bytes
wire=frame(crc32(origin_ms:u64be body):u32be origin_ms:u64be body)
handshake=wire(sig:32B dialer-addr:addr?)
addr=(host:str port:str name:str)
p2set=(adds:[addr] removes:[addr])
msg0=Pong
msg1=ExchangeAddrs(p2set)
msg2=AnnounceAddrs(p2set)
msg3=PushDeltas(name:str batch:[(key:bytes delta)])
msg4=SyncRequest(digests:[bytes] order=TREG,TLOG,GCOUNT,PNCOUNT,UJSON,TENSOR)
msg5=SyncDone
msg6=DeltaAck(cum:varint)
msg7=SeqPush(seq:varint name:str batch:[(key:bytes delta)])
msg8=DigestTree(name:str leaves:[(bucket:varint digest:bytes)] fanout=256 bucket=sha256(key)[0])
msg9=RangeRequest(name:str buckets:[varint])
msg10=IntervalReset(seq:varint)
delta/TREG=(value:bytes ts:varint)
delta/TLOG=delta/SYSTEM=(entries:[(value:bytes ts:varint)] cutoff:varint)
delta/GCOUNT=[(rid:varint v:varint)]
delta/PNCOUNT=(gcount gcount)
delta/UJSON=(entries:[(rid seq path:[str] token:str)] vv:[(rid seq)] cloud:[(rid seq)])
delta/TENSOR=(mode:varint dim:varint val:bytes ts:bytes rid:bytes contribs:[(rid:varint ts:varint vec:bytes)])
"""


def legacy_delta_signatures() -> tuple[bytes, ...]:
    """DELTA-schema digests of older releases whose frames this build
    still decodes, stamped into v4+ snapshot and journal headers on
    disk. Two windows: the v1-v6 delta lines (unchanged across that
    whole span) hash to one digest, and the v7/v8 lines (v7 added
    delta/TENSOR; v8 changed only transport messages) hash to another.
    v9 added delta/MAP + delta/BCOUNT — pure extensions, so every
    legacy file's frames still decode: they contain only old-type
    units."""
    out = []
    for text in (_LEGACY_V6_TEXT, _LEGACY_V8_TEXT):
        delta_lines = [
            line
            for line in text.splitlines()
            if line.startswith("delta/") or line.startswith("varint=")
        ]
        out.append(hashlib.sha256("\n".join(delta_lines).encode()).digest())
    return tuple(out)


def legacy_snapshot_signatures() -> tuple[bytes, ...]:
    """Snapshot headers older releases wrote that THIS build still reads:
    every frame they version is still decodable (persist.py accepts
    these alongside delta_signature(), so upgrading a single-node
    deployment never strands its only data copy). The early releases
    stamped the FULL schema signature; v4+ stamped the delta signature
    (now also legacy after the v7 delta/TENSOR addition)."""
    return (
        hashlib.sha256(_LEGACY_V1_TEXT.encode()).digest(),
        hashlib.sha256(_LEGACY_V2_TEXT.encode()).digest(),
        hashlib.sha256(_LEGACY_V3_TEXT.encode()).digest(),
    ) + legacy_delta_signatures()


# the reader primitives live in utils/wire.py (shared with the lazy wire
# objects in ops/ujson_wire.py); a WireError IS this module's CodecError
CodecError = WireError


def batch_has_content(name: str, batch) -> bool:
    """True when a flushed delta batch carries joinable content. Empty
    batches and the SYSTEM keepalive quirk (deltas_size()==1 even when
    the delta log is empty) ship nothing a receiver — or the delta
    journal — can use. The SYSTEM batch-shape knowledge lives here with
    the rest of the per-type delta shapes; the cluster held-delta filter
    and journal/journal.py both delegate to this one predicate."""
    if not batch:
        return False
    if name == "SYSTEM":
        return any(entries or cutoff for _, (entries, cutoff) in batch)
    return True


# ---- primitive writers ----------------------------------------------------


def _w_varint(out: bytearray, v: int) -> None:
    if v < 0:
        raise CodecError(f"negative varint: {v}")
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _w_bytes(out: bytearray, b: bytes) -> None:
    _w_varint(out, len(b))
    out.extend(b)


def _w_str(out: bytearray, s: str) -> None:
    _w_bytes(out, s.encode())


# ---- address / membership set ---------------------------------------------


def _w_addr(out: bytearray, a: Address) -> None:
    _w_str(out, a.host)
    _w_str(out, a.port)
    _w_str(out, a.name)


def _r_addr(r: _Reader) -> Address:
    return Address(r.str_(), r.str_(), r.str_())


def encode_addr(a: Address) -> bytes:
    """One bare address (the v5 handshake's dialer-identity suffix)."""
    out = bytearray()
    _w_addr(out, a)
    return bytes(out)


def decode_addr(data: bytes) -> Address:
    r = _Reader(data)
    a = _r_addr(r)
    if not r.done():
        raise CodecError("trailing bytes after address")
    return a


def encode_hello(a: Address, region: str, epoch: int) -> bytes:
    """The dialer's v10 handshake suffix: advertised address + region
    name + boot epoch (the session-rid incarnation stamp)."""
    out = bytearray()
    _w_addr(out, a)
    _w_str(out, region)
    _w_varint(out, epoch)
    return bytes(out)


def decode_hello(data: bytes) -> tuple[Address, str, int]:
    r = _Reader(data)
    a = _r_addr(r)
    region = r.str_()
    epoch = r.varint()
    if epoch > _U64_MAX:
        raise CodecError("hello epoch exceeds u64")
    if not r.done():
        raise CodecError("trailing bytes after hello")
    return a, region, epoch


def encode_echo(region: str, epoch: int) -> bytes:
    """The passive side's v10 handshake echo suffix."""
    out = bytearray()
    _w_str(out, region)
    _w_varint(out, epoch)
    return bytes(out)


def decode_echo(data: bytes) -> tuple[str, int]:
    r = _Reader(data)
    region = r.str_()
    epoch = r.varint()
    if epoch > _U64_MAX:
        raise CodecError("echo epoch exceeds u64")
    if not r.done():
        raise CodecError("trailing bytes after echo")
    return region, epoch


def _w_svec(out: bytearray, entries: tuple) -> None:
    # session vector: pre-sorted (rid, seq) pairs (sessions.py)
    _w_varint(out, len(entries))
    for rid, seq in entries:
        _w_str(out, rid)
        _w_varint(out, seq)


def _r_svec(r: _Reader) -> tuple:
    # accumulator deliberately NOT named `out`: pass 7's symbolic
    # evaluator reads `out.append` as the byte-writer primitive
    entries = []
    for _ in range(r.varint()):
        rid = r.str_()
        seq = r.varint()
        if seq > _U64_MAX:
            raise CodecError("svec seq exceeds u64")
        entries.append((rid, seq))
    return tuple(entries)


def _w_p2set(out: bytearray, s: P2Set) -> None:
    for group in (s.adds, s.removes):
        addrs = sorted(group, key=str)
        _w_varint(out, len(addrs))
        for a in addrs:
            _w_addr(out, a)


def _r_p2set(r: _Reader) -> P2Set:
    s = P2Set()
    s.adds = {_r_addr(r) for _ in range(r.varint())}
    s.removes = {_r_addr(r) for _ in range(r.varint())}
    return s


# ---- per-type delta payloads ----------------------------------------------


def _w_gcount_dict(out: bytearray, d: dict) -> None:
    _w_varint(out, len(d))
    for rid in sorted(d):
        _w_varint(out, rid)
        _w_varint(out, d[rid])


def _r_gcount_dict(r: _Reader) -> dict:
    return {r.varint(): r.varint() for _ in range(r.varint())}


def _w_tlog(out: bytearray, delta: tuple) -> None:
    entries, cutoff = delta
    _w_varint(out, len(entries))
    for value, ts in entries:
        _w_bytes(out, value)
        _w_varint(out, ts)
    _w_varint(out, cutoff)


def _r_tlog(r: _Reader) -> tuple:
    entries = [(r.bytes_(), r.varint()) for _ in range(r.varint())]
    return entries, r.varint()


def _w_ujson(out: bytearray, u: UJSON) -> None:
    _w_varint(out, len(u.entries))
    for (rid, seq) in sorted(u.entries):
        path, token = u.entries[(rid, seq)]
        _w_varint(out, rid)
        _w_varint(out, seq)
        _w_varint(out, len(path))
        for part in path:
            _w_str(out, part)
        _w_str(out, token)
    vv = u.ctx.vv
    _w_varint(out, len(vv))
    for rid in sorted(vv):
        _w_varint(out, rid)
        _w_varint(out, vv[rid])
    cloud = sorted(u.ctx.cloud)
    _w_varint(out, len(cloud))
    for rid, seq in cloud:
        _w_varint(out, rid)
        _w_varint(out, seq)


def _r_ujson(r: _Reader) -> UJSON:
    return read_ujson(r)  # single implementation: ops/ujson_wire.py


def _w_tensor(out: bytearray, t: Tensor) -> None:
    # uniform shape for all three merge modes (branch-free unit: pass 7)
    _w_varint(out, t.mode)
    _w_varint(out, t.dim)
    _w_bytes(out, t.val)
    _w_bytes(out, t.ts)
    _w_bytes(out, t.rid)
    _w_varint(out, len(t.contribs))
    for rid in sorted(t.contribs):
        cts, vec = t.contribs[rid]
        _w_varint(out, rid)
        _w_varint(out, cts)
        _w_bytes(out, vec)


def _r_tensor(r: _Reader) -> Tensor:
    mode = r.varint()
    dim = r.varint()
    val = r.bytes_()
    ts = r.bytes_()
    rid = r.bytes_()
    n = r.varint()
    contribs: dict[int, tuple[int, bytes]] = {}
    for _ in range(n):
        crid = r.varint()
        cts = r.varint()
        contribs[crid] = (cts, r.bytes_())
    if len(contribs) != n:
        # a repeated rid would silently last-entry-win past the per-rid
        # join — the canonical encoding never produces one
        raise CodecError("duplicate tensor contribution rid")
    # shape validation happens in from_wire; a WireError IS a CodecError
    return Tensor.from_wire(mode, dim, val, ts, rid, contribs)


def _w_map(out: bytearray, unit: tuple) -> None:
    # one FIELD's product-lattice unit (the v9 recursive shape): inner
    # type tag, edit counters, tombstone, then the inner type's OWN
    # delta encoding — branch-free (val is always present; the inner
    # bottom is the join identity, so a tombstone-only unit ships it)
    itype, ver, tomb, val = unit
    if itype not in compose.REGISTRY:
        raise CodecError(f"unregistered MAP value type: {itype}")
    _w_str(out, itype)
    _w_gcount_dict(out, ver)
    _w_gcount_dict(out, tomb)
    _w_delta(out, itype, val)


_U64_MAX = (1 << 64) - 1


def _r_u64_dict(r: _Reader) -> dict:
    """A {rid: amount} span with BOTH sides bounded to u64: LEB128
    admits ~2^70, and an oversized escrow amount or edit seq would be
    journaled, then poison every arithmetic consumer on replay (the
    TENSOR AVG-ts lesson)."""
    d = _r_gcount_dict(r)
    for rid, v in d.items():
        if rid > _U64_MAX or v > _U64_MAX:
            raise CodecError("rid or amount exceeds u64")
    return d


def _r_map(r: _Reader) -> tuple:
    itype = r.str_()
    if itype not in compose.REGISTRY:
        raise CodecError(f"unregistered MAP value type: {itype}")
    ver = _r_u64_dict(r)
    tomb = _r_u64_dict(r)
    val = _r_delta(r, itype)
    return (itype, ver, tomb, val)


def _w_xfer(out: bytearray, m: dict) -> None:
    # a transfer matrix {(from, to): amount} as sorted triples
    _w_varint(out, len(m))
    for (f, t) in sorted(m):
        _w_varint(out, f)
        _w_varint(out, t)
        _w_varint(out, m[(f, t)])


def _r_xfer(r: _Reader) -> dict:
    out: dict[tuple[int, int], int] = {}
    for _ in range(r.varint()):
        f = r.varint()
        t = r.varint()
        v = r.varint()
        if f > _U64_MAX or t > _U64_MAX or v > _U64_MAX:
            raise CodecError("rid or amount exceeds u64")
        out[(f, t)] = v
    return out


def _w_bcount(out: bytearray, wire: tuple) -> None:
    # the FULL per-key view, five join-monotone components (the
    # self-justifying-state rule: funding evidence never lags a spend)
    grants, incs, decs, xi, xd = wire
    _w_gcount_dict(out, grants)
    _w_gcount_dict(out, incs)
    _w_gcount_dict(out, decs)
    _w_xfer(out, xi)
    _w_xfer(out, xd)


def _r_bcount(r: _Reader) -> tuple:
    grants = _r_u64_dict(r)
    incs = _r_u64_dict(r)
    decs = _r_u64_dict(r)
    xi = _r_xfer(r)
    xd = _r_xfer(r)
    return (grants, incs, decs, xi, xd)


def _w_delta(out: bytearray, name: str, delta) -> None:
    if name == "TREG":
        value, ts = delta
        _w_bytes(out, value)
        _w_varint(out, ts)
    elif name in ("TLOG", "SYSTEM"):
        _w_tlog(out, delta)
    elif name == "GCOUNT":
        _w_gcount_dict(out, delta)
    elif name == "PNCOUNT":
        dp, dn = delta
        _w_gcount_dict(out, dp)
        _w_gcount_dict(out, dn)
    elif name == "UJSON":
        _w_ujson(out, delta)
    elif name == "TENSOR":
        _w_tensor(out, delta)
    elif name == "MAP":
        _w_map(out, delta)
    elif name == "BCOUNT":
        _w_bcount(out, delta)
    else:
        raise CodecError(f"unknown data type: {name}")


def _r_delta(r: _Reader, name: str):
    if name == "TREG":
        return r.bytes_(), r.varint()
    if name in ("TLOG", "SYSTEM"):
        return _r_tlog(r)
    if name == "GCOUNT":
        return _r_gcount_dict(r)
    if name == "PNCOUNT":
        return _r_gcount_dict(r), _r_gcount_dict(r)
    if name == "UJSON":
        return _r_ujson(r)
    if name == "TENSOR":
        return _r_tensor(r)
    if name == "MAP":
        return _r_map(r)
    if name == "BCOUNT":
        return _r_bcount(r)
    raise CodecError(f"unknown data type: {name}")


def encode_delta(name: str, delta) -> bytes:
    """One bare per-type delta payload (no message framing): what
    TENSOR MRG accepts as its binary bulk payload, and what tests use
    to pin delta bytes without a whole PushDeltas."""
    out = bytearray()
    _w_delta(out, name, delta)
    return bytes(out)


def decode_delta(name: str, blob: bytes):
    """Inverse of encode_delta; raises CodecError on trailing bytes."""
    r = _Reader(blob)
    delta = _r_delta(r, name)
    if not r.done():
        raise CodecError("trailing bytes after delta")
    return delta


# ---- messages --------------------------------------------------------------

_TAG_PONG = 0
_TAG_EXCHANGE = 1
_TAG_ANNOUNCE = 2
_TAG_PUSH = 3
_TAG_SYNC_REQ = 4
_TAG_SYNC_DONE = 5
_TAG_DELTA_ACK = 6
_TAG_SEQ_PUSH = 7
_TAG_DIGEST_TREE = 8
_TAG_RANGE_REQ = 9
_TAG_INTERVAL_RESET = 10
_TAG_RELAY_PUSH = 11
_TAG_REGION_GOSSIP = 12


def encode(msg: Msg) -> bytes:
    if isinstance(msg, MsgPushDeltas):
        from ..native import codec as ncodec

        fast = ncodec.encode_push(msg)
        if fast is not None:
            return fast
    elif isinstance(msg, MsgSeqPush):
        # msg7's name+batch bytes are msg3's after the tag+seq prefix
        # (pinned by the schema text), so the native per-key delta
        # packer serves the seq-stamped hot path too
        from ..native import codec as ncodec

        fast = ncodec.encode_push(MsgPushDeltas(msg.name, msg.batch))
        if fast is not None:
            out = bytearray((_TAG_SEQ_PUSH,))
            _w_varint(out, msg.seq)
            _w_varint(out, msg.oseq)
            _w_bytes(out, msg.span)
            out += fast[1:]
            return bytes(out)
    elif isinstance(msg, MsgRelayPush):
        # msg11's name+batch bytes are msg3's after the
        # tag+seq+origin+oseq prefix (schema text), same native reuse
        from ..native import codec as ncodec

        fast = ncodec.encode_push(MsgPushDeltas(msg.name, msg.batch))
        if fast is not None:
            out = bytearray((_TAG_RELAY_PUSH,))
            _w_varint(out, msg.seq)
            _w_str(out, msg.origin)
            _w_varint(out, msg.oseq)
            _w_bytes(out, msg.span)
            out += fast[1:]
            return bytes(out)
    return _encode_oracle(msg)


def _encode_oracle(msg: Msg) -> bytes:
    out = bytearray()
    if isinstance(msg, MsgPong):
        out.append(_TAG_PONG)
    elif isinstance(msg, MsgSyncDone):
        out.append(_TAG_SYNC_DONE)
        _w_svec(out, msg.svec)
    elif isinstance(msg, MsgExchangeAddrs):
        out.append(_TAG_EXCHANGE)
        _w_p2set(out, msg.known_addrs)
    elif isinstance(msg, MsgAnnounceAddrs):
        out.append(_TAG_ANNOUNCE)
        _w_p2set(out, msg.known_addrs)
    elif isinstance(msg, MsgPushDeltas):
        out.append(_TAG_PUSH)
        _w_str(out, msg.name)
        _w_varint(out, len(msg.batch))
        for key, delta in msg.batch:
            _w_bytes(out, key)
            _w_delta(out, msg.name, delta)
    elif isinstance(msg, MsgSyncRequest):
        out.append(_TAG_SYNC_REQ)
        _w_varint(out, len(msg.digests))
        for d in msg.digests:
            _w_bytes(out, d)
        _w_svec(out, msg.svec)
    elif isinstance(msg, MsgDeltaAck):
        out.append(_TAG_DELTA_ACK)
        _w_varint(out, msg.cum)
    elif isinstance(msg, MsgSeqPush):
        out.append(_TAG_SEQ_PUSH)
        _w_varint(out, msg.seq)
        _w_varint(out, msg.oseq)
        _w_bytes(out, msg.span)
        _w_str(out, msg.name)
        _w_varint(out, len(msg.batch))
        for key, delta in msg.batch:
            _w_bytes(out, key)
            _w_delta(out, msg.name, delta)
    elif isinstance(msg, MsgDigestTree):
        out.append(_TAG_DIGEST_TREE)
        _w_str(out, msg.name)
        _w_varint(out, len(msg.leaves))
        for bucket, digest in msg.leaves:
            _w_varint(out, bucket)
            _w_bytes(out, digest)
    elif isinstance(msg, MsgRangeRequest):
        out.append(_TAG_RANGE_REQ)
        _w_str(out, msg.name)
        _w_varint(out, len(msg.buckets))
        for bucket in msg.buckets:
            _w_varint(out, bucket)
    elif isinstance(msg, MsgIntervalReset):
        out.append(_TAG_INTERVAL_RESET)
        _w_varint(out, msg.seq)
    elif isinstance(msg, MsgRelayPush):
        out.append(_TAG_RELAY_PUSH)
        _w_varint(out, msg.seq)
        _w_str(out, msg.origin)
        _w_varint(out, msg.oseq)
        _w_bytes(out, msg.span)
        _w_str(out, msg.name)
        _w_varint(out, len(msg.batch))
        for key, delta in msg.batch:
            _w_bytes(out, key)
            _w_delta(out, msg.name, delta)
    elif isinstance(msg, MsgRegionGossip):
        out.append(_TAG_REGION_GOSSIP)
        _w_varint(out, len(msg.regions))
        for addr_s, region, epoch in msg.regions:
            _w_str(out, addr_s)
            _w_str(out, region)
            _w_varint(out, epoch)
    else:
        raise CodecError(f"cannot encode {type(msg).__name__}")
    return bytes(out)


def decode(body: bytes) -> Msg:
    if body and body[0] == _TAG_PUSH:
        from ..native import codec as ncodec

        fast = ncodec.decode_push(body)
        if fast is not None:
            return fast
    elif body and body[0] == _TAG_SEQ_PUSH:
        # strip the seq prefix, decode the remainder as msg3 (native
        # fast path or oracle — byte-identical by schema), re-tag
        from ..native import codec as ncodec

        r = _Reader(body)
        r.pos = 1
        seq = r.varint()
        oseq = r.varint()
        if seq > _U64_MAX or oseq > _U64_MAX:
            raise CodecError("seq exceeds u64")
        span = r.bytes_()
        rest = bytes((_TAG_PUSH,)) + body[r.pos :]
        fast = ncodec.decode_push(rest)
        inner = fast if fast is not None else _decode_oracle(rest)
        return MsgSeqPush(seq, oseq, inner.name, inner.batch, span)
    elif body and body[0] == _TAG_RELAY_PUSH:
        # same trick for the relay: strip tag+seq+origin+oseq, decode
        # the remainder as msg3, re-tag
        from ..native import codec as ncodec

        r = _Reader(body)
        r.pos = 1
        seq = r.varint()
        origin = r.str_()
        oseq = r.varint()
        if seq > _U64_MAX or oseq > _U64_MAX:
            raise CodecError("relay seq exceeds u64")
        span = r.bytes_()
        rest = bytes((_TAG_PUSH,)) + body[r.pos :]
        fast = ncodec.decode_push(rest)
        inner = fast if fast is not None else _decode_oracle(rest)
        return MsgRelayPush(seq, origin, oseq, inner.name, inner.batch, span)
    return _decode_oracle(body)


def _decode_oracle(body: bytes) -> Msg:
    r = _Reader(body)
    if not body:
        raise CodecError("empty message")
    tag = body[0]
    r.pos = 1
    if tag == _TAG_PONG:
        msg: Msg = MsgPong()
    elif tag == _TAG_SYNC_DONE:
        msg = MsgSyncDone(_r_svec(r))
    elif tag == _TAG_EXCHANGE:
        msg = MsgExchangeAddrs(_r_p2set(r))
    elif tag == _TAG_ANNOUNCE:
        msg = MsgAnnounceAddrs(_r_p2set(r))
    elif tag == _TAG_PUSH:
        name = r.str_()
        batch = tuple(
            (r.bytes_(), _r_delta(r, name)) for _ in range(r.varint())
        )
        msg = MsgPushDeltas(name, batch)
    elif tag == _TAG_SYNC_REQ:
        digests = tuple(r.bytes_() for _ in range(r.varint()))
        msg = MsgSyncRequest(digests, _r_svec(r))
    elif tag == _TAG_DELTA_ACK:
        msg = MsgDeltaAck(r.varint())
    elif tag == _TAG_SEQ_PUSH:
        seq = r.varint()
        oseq = r.varint()
        if seq > _U64_MAX or oseq > _U64_MAX:
            raise CodecError("seq exceeds u64")
        span = r.bytes_()
        name = r.str_()
        batch = tuple(
            (r.bytes_(), _r_delta(r, name)) for _ in range(r.varint())
        )
        msg = MsgSeqPush(seq, oseq, name, batch, span)
    elif tag == _TAG_DIGEST_TREE:
        name = r.str_()
        leaves = tuple(
            (r.varint(), r.bytes_()) for _ in range(r.varint())
        )
        msg = MsgDigestTree(name, leaves)
    elif tag == _TAG_RANGE_REQ:
        name = r.str_()
        buckets = tuple(r.varint() for _ in range(r.varint()))
        msg = MsgRangeRequest(name, buckets)
    elif tag == _TAG_INTERVAL_RESET:
        msg = MsgIntervalReset(r.varint())
    elif tag == _TAG_RELAY_PUSH:
        seq = r.varint()
        origin = r.str_()
        oseq = r.varint()
        if seq > _U64_MAX or oseq > _U64_MAX:
            raise CodecError("relay seq exceeds u64")
        span = r.bytes_()
        name = r.str_()
        batch = tuple(
            (r.bytes_(), _r_delta(r, name)) for _ in range(r.varint())
        )
        msg = MsgRelayPush(seq, origin, oseq, name, batch, span)
    elif tag == _TAG_REGION_GOSSIP:
        entries = []
        for _ in range(r.varint()):
            addr_s = r.str_()
            region = r.str_()
            epoch = r.varint()
            if epoch > _U64_MAX:
                raise CodecError("gossip epoch exceeds u64")
            entries.append((addr_s, region, epoch))
        msg = MsgRegionGossip(tuple(entries))
    else:
        raise CodecError(f"unknown message tag: {tag}")
    if not r.done():
        raise CodecError("trailing bytes after message")
    return msg
