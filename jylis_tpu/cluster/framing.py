"""Cluster wire framing: magic byte + 64-bit big-endian length header.

Reference analog: framing.pony:1-28 — a 9-byte header (magic ``0x06``
followed by the body length as an 8-byte big-endian integer); parsing
validates the magic byte and rejects the frame otherwise. The reference
additionally guards for 64-bit platforms at compile time (framing.pony:3);
Python ints make that moot, but we keep the explicit u64 bound check.

The header is 9 fixed bytes built/parsed with ``struct`` — there is
deliberately no native twin for it (nothing to win); the codec underneath
the framing (cluster/codec.py) is where the native fast path lives
(native/cluster_codec.cpp).
"""

from __future__ import annotations

import struct

MAGIC = 0x06
HEADER_SIZE = 9
_U64_MAX = (1 << 64) - 1


class FramingError(Exception):
    """Bad magic or impossible length — treated like auth failure
    (framed_notify.pony:70-71: the connection is dropped)."""


def build_header(body_len: int) -> bytes:
    if not (0 <= body_len <= _U64_MAX):
        raise FramingError(f"body length out of u64 range: {body_len}")
    return struct.pack(">BQ", MAGIC, body_len)


def parse_header(header: bytes) -> int:
    """Returns the body length; raises FramingError on a tampered magic
    byte (framing.pony:20) or short header."""
    if len(header) != HEADER_SIZE:
        raise FramingError(f"header must be {HEADER_SIZE} bytes, got {len(header)}")
    magic, length = struct.unpack(">BQ", header)
    if magic != MAGIC:
        raise FramingError(f"bad magic byte: {magic:#x}")
    return length


def frame(body: bytes) -> bytes:
    """Wrap a message body for the wire (framed_notify.pony:50-54)."""
    return build_header(len(body)) + body


class FrameReader:
    """Incremental frame reassembly over a byte stream.

    The reference alternates ``conn.expect(header)`` / ``expect(body)``
    (framed_notify.pony:42-48,64-77); asyncio gives us a buffer instead, so
    this class carries the same state machine over an internal buffer.
    Frames larger than ``max_frame`` raise, bounding memory under a
    malicious or corrupt peer.
    """

    def __init__(self, max_frame: int = 1 << 30):
        self._buf = bytearray()
        self._need: int | None = None  # body length once header parsed
        self._max = max_frame

    def append(self, data: bytes) -> None:
        self._buf.extend(data)

    def set_max_frame(self, max_frame: int) -> None:
        """Raise/lower the frame cap (used to widen after a handshake)."""
        self._max = max_frame

    def pending(self) -> int:
        """Bytes buffered but not yet yielded as a complete frame (useful
        for end-of-stream truncation checks)."""
        return len(self._buf) + (0 if self._need is None else HEADER_SIZE)

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        if self._need is None:
            if len(self._buf) < HEADER_SIZE:
                raise StopIteration
            self._need = parse_header(bytes(self._buf[:HEADER_SIZE]))
            if self._need > self._max:
                raise FramingError(f"frame of {self._need} bytes exceeds limit")
            del self._buf[:HEADER_SIZE]
        if len(self._buf) < self._need:
            raise StopIteration
        body = bytes(self._buf[: self._need])
        del self._buf[: self._need]
        self._need = None
        return body
